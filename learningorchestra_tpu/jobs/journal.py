"""Crash-durable job journal + engine-epoch execution fencing.

The engine's queue, running set and lease bookkeeping are in-memory:
before this module, a ``kill -9`` of the orchestrator silently lost
every queued job and stranded RUNNING jobs as forever-"running"
metadata — exactly the durability gap the reference system's
"stateful, persisted, independently re-executable" pipeline-step
contract promises away (PAPER.md).  Two pieces close it:

**Journal.**  Every job state transition (``submitted → queued →
running(attempt N) → finished | failed | cancelled``, plus
``preempted``/``deadline``/``cancel_requested`` events) is appended to
the ``_job_journal`` store collection BEFORE the in-memory transition
commits.  The collection rides the document store's existing WAL
machinery (document_store.py), so journal records get the same
torn-tail recovery, compaction and WAL-shipping (store/replica.py —
a promoted standby inherits the journal) as every artifact.  Records
are keyed by job name and carry the submit spec (method, parameters,
class, deadline), so the full engine state is reconstructible from
the journal alone: :meth:`JobJournal.replay` folds the records into
one terminal-or-latest state per job, preserving queue admission
order.

**Epoch fencing.**  Each recovery boot mints an **engine epoch** — a
monotonic counter in ``.engine_epoch`` inside the store root, the
same idiom as the HA tier's ``.epoch`` election term
(store/replica.py) but scoped to engine restarts over ONE store
directory.  The engine stamps the boot epoch on every dispatched job
body (a contextvar, like the retry attempt); terminal metadata
commits and artifact publications re-read the durable file and
refuse to commit when a NEWER epoch exists (:func:`JobJournal.
fence_check` raises :class:`StaleEpochError`).  A pre-crash straggler
thread that somehow survives into a recovered world — or, once the
control plane goes multi-process (ROADMAP item 4), a partitioned
duplicate orchestrator over the shared store — cannot double-publish
artifacts or lost-update job metadata.

Cost discipline: every journal record — the submit pair included —
is GROUP-COMMITTED: the hot path enqueues a slim record (one deque
append) and an eager flusher drains FIFO batches into the store's
WAL within the time of one batch write.  The sub-ms window this
opens is harmless by construction: recovery is metadata-authoritative
(the artifact's own collection records the same transitions, flushed
inline, with the request parameters stamped at submit), so a crash
inside the window can at worst demote a job from auto-re-dispatch to
the explicit orphaned-by-restart path — never lose or double-run
one.  ``bench._journal_probe`` banks the resulting
submit/dispatch-path cost below 2% of a minimal job dispatch.  Fence
checks re-read a one-line file and run only at terminal
commits/publications, never per epoch.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from collections import deque
from pathlib import Path

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.store.document_store import DocumentStore

logger = get_logger("journal")

#: Store collection holding journal records.  Underscore prefix keeps
#: it out of the artifact namespace (same convention as the
#: idempotency ledger) and sorts it early in WAL shipping.
JOURNAL_COLLECTION = "_job_journal"

#: Engine-epoch counter file inside the store root — the restart
#: analogue of the HA tier's ``.epoch`` election term.
ENGINE_EPOCH_FILE = ".engine_epoch"

#: Journal events that end a job's life.  Everything else is
#: non-terminal: a restart must recover the job.
TERMINAL_EVENTS = frozenset(
    {"finished", "failed", "cancelled", "deadline"}
)

#: Every event the engine journals — the replay goldens enumerate
#: these (tests/test_journal_recovery.py).
EVENTS = (
    "submitted",
    "queued",
    "running",
    "preempted",
    "cancel_requested",
    "finished",
    "failed",
    "cancelled",
    "deadline",
)


class StaleEpochError(RuntimeError):
    """A worker from an older engine epoch tried to commit: a newer
    recovery (or a duplicate orchestrator over the shared store) owns
    this store now — the write is refused, not merged."""


def read_engine_epoch(store_root: str | Path) -> int:
    """The store's engine epoch; 0 for a store no engine booted on."""
    try:
        return int((Path(store_root) / ENGINE_EPOCH_FILE).read_text())
    except (OSError, ValueError):
        return 0


def write_engine_epoch(store_root: str | Path, epoch: int) -> None:
    """Durably publish ``epoch`` (write + fsync + atomic replace):
    fencing is only as strong as this file's crash-durability."""
    root = Path(store_root)
    root.mkdir(parents=True, exist_ok=True)
    tmp = root / (ENGINE_EPOCH_FILE + ".tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(str(int(epoch)))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, root / ENGINE_EPOCH_FILE)


#: The dispatched job body's engine epoch (None outside a dispatch —
#: direct library use keeps working, unfenced).
_STAMP: contextvars.ContextVar = contextvars.ContextVar(
    "lo_engine_epoch", default=None
)


def current_stamp() -> int | None:
    """The engine epoch stamped on the calling job body's dispatch."""
    return _STAMP.get()


@contextlib.contextmanager
def stamp(epoch: int | None):
    """Bind ``epoch`` as the current body's engine epoch (the engine
    wraps each dispatch; tests bind stale values to drive the fence)."""
    handle = _STAMP.set(epoch)
    try:
        yield
    finally:
        _STAMP.reset(handle)


class JobJournal:
    """Append/replay surface over the ``_job_journal`` collection.

    Thread-safety: writes delegate to the document store, whose
    per-collection lock serializes WAL appends and allocates
    monotonic ``_id`` sequence numbers.  The group-commit flusher is
    serialized by ``_flush_lock`` (drains never interleave, so batch
    order equals enqueue order).
    """

    # ``documents`` is annotated DocumentStore for the whole-program
    # lock analyzer's constructor-typed-attribute resolution (the
    # native backend shares the API; the annotation is the static
    # model, not a runtime constraint).
    def __init__(self, documents: DocumentStore,
                 store_root: str | Path, *,
                 enabled: bool = True, max_records: int = 4096,
                 epoch_lock=None):
        self.documents = documents
        self.store_root = Path(store_root)
        self.enabled = bool(enabled)
        self.max_records = int(max_records)
        #: Zero-arg callable returning a context manager that holds
        #: the CLUSTER's cross-process lock (services/context.py wires
        #: the coordinator's guard in).  Epoch minting runs under it
        #: so two engines booting concurrently over one store root
        #: mint distinct epochs.  None → single-process boot, no lock.
        self._epoch_lock = epoch_lock
        #: Under clustering (jobs/cluster.py) the context sets these:
        #: ``cluster`` delegates the fence to claim ownership, and
        #: ``exclusive`` (a zero-arg guard factory refreshing the
        #: journal collection) serializes cross-process appends so
        #: two engines cannot allocate conflicting ``_id`` sequence
        #: numbers.  Both None in the single-engine world — the hot
        #: path pays one attribute check.
        self.cluster = None
        self.exclusive = None
        #: Appends that failed (store fault, disk full) — surfaced so
        #: a silently lossy journal is at least countable.
        self.dropped = 0
        # Group-commit state: the hot path enqueues (GIL-atomic deque
        # append) and wakes the flusher; drains are serialized.
        self._pending: deque = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._flush_lock = make_lock("JobJournal._flush_lock")
        self._flusher: threading.Thread | None = None
        # Each construction is an engine boot: mint the next epoch so
        # stragglers from any previous life are fenced at commit time.
        # Disabled journals keep epoch 0 and never fence.
        self.epoch = (
            self._mint_epoch() if self.enabled else 0
        )

    # -- epoch fencing --------------------------------------------------------

    def _mint_epoch(self) -> int:
        lock = (
            self._epoch_lock() if self._epoch_lock is not None
            else contextlib.nullcontext()
        )
        with lock:
            epoch = read_engine_epoch(self.store_root) + 1
            write_engine_epoch(self.store_root, epoch)
        logger.info(kv(event="engine_epoch_minted", epoch=epoch))
        return epoch

    def durable_epoch(self) -> int:
        """The store's CURRENT epoch, re-read from disk — what a
        newer recovery (or duplicate orchestrator) would have bumped."""
        return read_engine_epoch(self.store_root)

    def fence_check(self, stamped: int | None = None) -> None:
        """Refuse a commit from a stale engine epoch.

        ``stamped`` defaults to the calling job body's dispatch stamp;
        unstamped callers (direct library use, tests without an
        engine) pass the check — fencing guards engine-dispatched
        work, not ad-hoc scripts.
        """
        if not self.enabled:
            return
        if stamped is None:
            stamped = current_stamp()
        if stamped is None:
            return
        if self.cluster is not None:
            # Multi-engine world: two LIVE engines legitimately hold
            # different durable epochs, so the single-process
            # "newer epoch exists" comparison is wrong here.  The
            # fence becomes claim OWNERSHIP: a cluster dispatch may
            # commit only while its engine still owns the live claim
            # under the stamped epoch — a stolen claim (partition,
            # missed heartbeats) refuses the straggler's publication.
            from learningorchestra_tpu.jobs.cluster import current_claim

            claim = current_claim()
            if claim is None:
                return  # direct library use on a clustered store
            if not self.cluster.verify(claim, stamped):
                from learningorchestra_tpu.obs import flight as obs_flight

                obs_flight.record(
                    "cluster", "fence_refused", job=claim,
                    engine=self.cluster.engine_id, epoch=stamped,
                )
                raise StaleEpochError(
                    f"claim for job {claim!r} is no longer owned by "
                    f"engine {self.cluster.engine_id!r} under epoch "
                    f"{stamped} — the claim was stolen or released by "
                    "a peer; refusing to commit"
                )
            return
        durable = self.durable_epoch()
        if durable > stamped:
            raise StaleEpochError(
                f"engine epoch {stamped} is stale: the store's "
                f"current epoch is {durable} — a newer recovery owns "
                "this store; refusing to commit"
            )

    # -- append ---------------------------------------------------------------

    def record_submit(self, job: str, *, job_class: str,
                      method=None, description=None, parameters=None,
                      deadline_s=None, request_id=None) -> None:
        """The ``submitted``+``queued`` pair, enqueued as adjacent
        records in the group-commit FIFO (one WAL batch, durable
        within the flusher's next drain — see the module docstring
        for why the window is safe).

        ``parameters`` are NOT copied into the journal — the engine
        already stamps them durably into the artifact's metadata
        (``requestParameters``) BEFORE journaling, and recovery
        re-dispatches through ``last_recorded_parameters``;
        duplicating a possibly-large request body here would put its
        serialization cost on every submit."""
        if not self.enabled:
            return
        del parameters  # recorded in artifact metadata (see above)
        spec = {"jobClass": job_class}
        if method is not None:
            spec["method"] = method
        if description is not None:
            spec["description"] = description
        if deadline_s is not None:
            spec["deadlineS"] = deadline_s
        if request_id is not None:
            spec["requestId"] = request_id
        base = {
            "docType": "journal",
            "job": job,
            "epoch": self.epoch,
            "at": time.time(),
        }
        self._pending.append(
            {**base, "event": "submitted", "spec": spec}
        )
        self._enqueue({**base, "event": "queued"})

    def append(self, event: str, job: str, *, attempt=None,
               reason=None) -> None:
        """One transition record, group-committed: the hot path is a
        deque append + flusher wake; the flusher drains FIFO batches
        into the store's WAL within one batch-write time.  Recovery
        stays correct across the sub-ms window because the artifact's
        own metadata (flushed inline by the engine, and stamped with
        the request parameters at submit) is authoritative — the
        journal adds the spec, ordering and event detail; at worst a
        crash inside the window demotes a job from auto-re-dispatch
        to the explicit orphaned-by-restart path."""
        if not self.enabled:
            return
        doc = {
            "docType": "journal",
            "job": job,
            "event": event,
            "epoch": self.epoch,
            "at": time.time(),
        }
        if attempt is not None:
            doc["attempt"] = attempt
        if reason is not None:
            doc["reason"] = reason
        self._enqueue(doc)

    # -- group-commit flusher -------------------------------------------------

    def _enqueue(self, doc: dict) -> None:
        self._pending.append(doc)
        if self._stop.is_set():
            # Late append after close() (a straggler body journaling
            # its terminal under shutdown_drain_s=0): the flusher is
            # gone — write through inline.  If the store already
            # closed, _drain counts the loss in `dropped` instead of
            # silently eating it.
            self._drain()
            return
        self._wake.set()
        if self._flusher is None:
            self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        with self._flush_lock:
            if self._flusher is None or not self._flusher.is_alive():
                self._flusher = threading.Thread(
                    target=self._flush_loop,
                    name="lo-job-journal", daemon=True,
                )
                self._flusher.start()

    def _flush_loop(self) -> None:
        while True:
            self._wake.wait(0.2)
            self._wake.clear()
            self._drain()
            if self._stop.is_set() and not self._pending:
                return

    def _drain(self) -> int:
        """Write every enqueued record, in order; returns the count.
        Serialized so concurrent drains (flusher + submit + close)
        can never interleave batch order."""
        with self._flush_lock:
            batch = []
            while self._pending:
                batch.append(self._pending.popleft())
            if not batch:
                return 0
            # Under clustering, appends run inside the coordinator's
            # cross-process guard (flock + WAL refresh): two engines
            # draining concurrently would otherwise allocate the same
            # ``_id`` sequence numbers from stale in-memory tails.
            guard = (
                self.exclusive() if self.exclusive is not None
                else contextlib.nullcontext()
            )
            try:
                with guard:
                    self.documents.insert_many(
                        JOURNAL_COLLECTION, batch
                    )
            except Exception:  # noqa: BLE001
                self.dropped += len(batch)
                logger.error(kv(event="journal_append_failed",
                                batch=len(batch)))
            return len(batch)

    def flush(self) -> None:
        """Drain synchronously — shutdown and tests call this before
        reading the journal back."""
        if self.enabled:
            self._drain()

    def close(self) -> None:
        """Stop the flusher after a final synchronous drain.  Call
        BEFORE closing the document store (a drain into closed WAL
        handles would count every record dropped)."""
        self._stop.set()
        self._wake.set()
        flusher = self._flusher
        if flusher is not None and flusher.is_alive():
            flusher.join(timeout=2.0)
        self.flush()

    # -- replay ---------------------------------------------------------------

    def replay(self) -> dict:
        """Fold the journal into one record per job, in queue
        admission order (insertion ``_id`` is the sequence number).

        Returns ``{job: {"state", "terminal", "spec", "attempts",
        "epoch", "seq"}}`` where ``seq`` is the job's LATEST
        ``queued`` sequence number — re-enqueueing recovered jobs in
        ``seq`` order preserves the pre-crash queue order.
        """
        if not self.enabled:
            return {}
        self.flush()  # same-process readers see enqueued records
        if self.exclusive is not None:
            # Fold peer engines' appends in before reading (the guard
            # refreshes the journal collection from its WAL).
            with self.exclusive():
                docs = list(
                    self.documents.find(JOURNAL_COLLECTION)
                ) if self.documents.collection_exists(
                    JOURNAL_COLLECTION
                ) else []
        elif not self.documents.collection_exists(JOURNAL_COLLECTION):
            return {}
        else:
            docs = self.documents.find(JOURNAL_COLLECTION)
        out: dict = {}
        for doc in docs:
            if doc.get("docType") != "journal" or not doc.get("job"):
                continue
            job = doc["job"]
            event = doc.get("event")
            rec = out.setdefault(job, {
                "state": "submitted", "terminal": False,
                "spec": None, "attempts": 0, "epoch": 0, "seq": -1,
            })
            rec["epoch"] = max(rec["epoch"], doc.get("epoch", 0))
            if event == "submitted":
                rec["spec"] = doc.get("spec") or rec["spec"]
                if rec["terminal"]:
                    # Re-submission of a completed job (PATCH re-run):
                    # a fresh life starts.
                    rec.update(terminal=False, attempts=0)
                rec["state"] = "submitted"
            elif event == "queued":
                rec["state"] = "queued"
                rec["terminal"] = False
                rec["seq"] = doc["_id"]
            elif event == "running":
                rec["state"] = "running"
                rec["attempts"] = max(
                    rec["attempts"], doc.get("attempt", 1)
                )
            elif event == "preempted":
                rec["state"] = "running"
            elif event == "cancel_requested":
                rec["state"] = "cancelling"
            elif event in TERMINAL_EVENTS:
                rec["state"] = (
                    "failed" if event == "deadline" else event
                )
                rec["terminal"] = True
                if doc.get("reason"):
                    rec["reason"] = doc["reason"]
        return out

    # -- maintenance ----------------------------------------------------------

    def prune(self) -> int:
        """Boot-time compaction: once the journal exceeds
        ``max_records``, drop all but the last record of each
        TERMINAL job (non-terminal jobs keep their full history —
        recovery needs it) and compact the backing WAL.  Returns the
        number of records dropped."""
        if not self.enabled or self.max_records <= 0:
            return 0
        if not self.documents.collection_exists(JOURNAL_COLLECTION):
            return 0
        if self.documents.count(JOURNAL_COLLECTION) <= self.max_records:
            return 0
        replayed = self.replay()
        terminal = {
            job for job, rec in replayed.items() if rec["terminal"]
        }
        last_seen: dict = {}
        for doc in self.documents.find(JOURNAL_COLLECTION):
            if doc.get("job") in terminal:
                last_seen[doc["job"]] = doc["_id"]
        dropped = 0
        for doc in self.documents.find(JOURNAL_COLLECTION):
            job = doc.get("job")
            if job in terminal and doc["_id"] != last_seen.get(job):
                self.documents.delete_one(
                    JOURNAL_COLLECTION, doc["_id"]
                )
                dropped += 1
        if dropped:
            try:
                self.documents.compact(JOURNAL_COLLECTION)
            except Exception:  # noqa: BLE001 — compaction is an
                pass  # optimization; the deletes already landed
            logger.info(kv(event="journal_pruned", dropped=dropped))
        return dropped
