"""Vision models: MNIST CNN and ResNet.

The reference reaches ResNet50 through ``tensorflow.keras.applications``
in the model service (reference: microservices/model_image/model.py:92-162,
README demo pipelines at README.md:53).  Here they are Flax modules:

- convolutions in NHWC (TPU-native layout; XLA tiles convs onto the MXU);
- GroupNorm instead of BatchNorm — batch-statistics-free, so the module is
  a pure function of (params, x): no mutable state collections to thread
  through jit/shard_map, and normalization is independent of the
  data-parallel batch split (BatchNorm under DP needs cross-replica stats
  sync, a host of complexity the reference's Horovod path simply got wrong
  by using per-replica stats).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from learningorchestra_tpu.ops.layers import remat_block
from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import NeuralEstimator

_MODULE = "learningorchestra_tpu.models.vision"


class _MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        # Accept (B, 784) flat or (B, 28, 28) or (B, 28, 28, 1).
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 28, 28, 1))
        elif x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class MnistCNN(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 10,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.num_classes = num_classes
        super().__init__(
            _MnistCNN(num_classes=num_classes),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


class _ResNetBlock(nn.Module):
    filters: int
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), self.strides, use_bias=False
            )(x)
            residual = nn.GroupNorm(num_groups=min(32, self.filters))(
                residual
            )
        return nn.relu(y + residual)


class _BottleneckBlock(nn.Module):
    filters: int
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(32, 4 * self.filters))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                4 * self.filters, (1, 1), self.strides, use_bias=False
            )(x)
            residual = nn.GroupNorm(num_groups=min(32, 4 * self.filters))(
                residual
            )
        return nn.relu(y + residual)


def space_to_depth(x, block: int = 2):
    """[B, H, W, C] → [B, H/block, W/block, C·block²] by folding each
    spatial block into channels (odd tails zero-padded).

    The MXU sees convolutions as [spatial·C_in → C_out] contractions;
    an RGB stem's C_in=3 pads to 8 of the 128 systolic lanes, wasting
    >90% of the array on ~12% of ResNet's FLOPs.  Folding 2×2 pixels
    into channels turns the stem into a ≥128-deep contraction at a
    quarter of the spatial positions — the standard public TPU ResNet
    recipe (see ROOFLINE.md).
    """
    b, h, w, c = x.shape
    pad_h = (-h) % block
    pad_w = (-w) % block
    if pad_h or pad_w:
        x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        h, w = h + pad_h, w + pad_w
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, c * block * block)


class _ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: type
    num_classes: int = 1000
    width: int = 64
    # jax.checkpoint each residual block: activations rematerialize in
    # the backward pass — the batch-size headroom knob for conv nets,
    # where activation HBM (B x H x W x C per block) dominates params.
    remat: bool | str = False
    # Opt-in MXU-friendly stem: space-to-depth(2) + 4×4/s1 conv in the
    # folded space — the same receptive field (8×8 ⊇ 7×7) and the same
    # output shape as conv7×7/s2, but a 4·4·4C-deep contraction
    # instead of a 3-channel one.  Default OFF: the parameter shape
    # differs, and stored artifacts trained with the classic stem must
    # keep loading.
    s2d_stem: bool = False

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        if self.s2d_stem:
            x = space_to_depth(x, 2)
            x = nn.Conv(self.width, (4, 4), (1, 1), use_bias=False,
                        name="stem_s2d")(x)
        else:
            x = nn.Conv(self.width, (7, 7), (2, 2), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=min(32, self.width))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = remat_block(self.block, self.remat)
        idx = 0
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block_i in range(n_blocks):
                strides = (2, 2) if stage > 0 and block_i == 0 else (1, 1)
                # Explicit names pinned to the historical auto-names
                # (sequential across stages) so stored artifacts survive
                # toggling the memory knob — same convention as
                # BertEncoder's remat (models/text.py).
                x = block_cls(
                    self.width * (2**stage), strides=strides,
                    name=f"{self.block.__name__}_{idx}",
                )(x)
                idx += 1
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class ResNet18(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        learning_rate: float = 1e-3,
        seed: int = 0,
        remat: bool | str = False,
        s2d_stem: bool = False,
    ):
        self.num_classes = num_classes
        self.remat = remat
        self.s2d_stem = s2d_stem
        super().__init__(
            _ResNet(
                stage_sizes=(2, 2, 2, 2),
                block=_ResNetBlock,
                num_classes=num_classes,
                remat=remat,
                s2d_stem=s2d_stem,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


@register(_MODULE)
class ResNet50(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        learning_rate: float = 1e-3,
        seed: int = 0,
        remat: bool | str = False,
        s2d_stem: bool = False,
    ):
        self.num_classes = num_classes
        self.remat = remat
        self.s2d_stem = s2d_stem
        super().__init__(
            _ResNet(
                stage_sizes=(3, 4, 6, 3),
                block=_BottleneckBlock,
                num_classes=num_classes,
                remat=remat,
                s2d_stem=s2d_stem,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


# -- VGG ---------------------------------------------------------------------


class _VGG(nn.Module):
    """VGG-16 layout (Simonyan & Zisserman config D), GroupNorm'd."""

    num_classes: int
    stage_sizes: Sequence[int] = (2, 2, 3, 3, 3)
    widths: Sequence[int] = (64, 128, 256, 512, 512)

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        for blocks, width in zip(self.stage_sizes, self.widths):
            for _ in range(blocks):
                x = nn.Conv(width, (3, 3), padding="SAME")(x)
                x = nn.GroupNorm(num_groups=math.gcd(32, width))(x)
                x = nn.relu(x)
            # SAME-padded pooling: small inputs (e.g. 28x28 MNIST) must
            # not shrink to a zero-size axis (VALID would: 28->...->0,
            # making global average pooling return NaN).
            x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
        x = x.mean(axis=(1, 2))  # GAP replaces the 4096-wide FC stack
        x = nn.relu(nn.Dense(1024)(x))
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class VGG16(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.num_classes = num_classes
        super().__init__(
            _VGG(num_classes=num_classes),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


# -- MobileNet ---------------------------------------------------------------


class _DepthwiseSeparable(nn.Module):
    """Depthwise (feature_group_count=C) + pointwise conv pair."""

    filters: int
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        x = nn.Conv(
            channels, (3, 3), strides=self.strides, padding="SAME",
            feature_group_count=channels,
        )(x)
        # gcd: group count must DIVIDE the channel count, which
        # arbitrary width multipliers (0.75 -> 48 channels) break for a
        # fixed 32.
        x = nn.GroupNorm(num_groups=math.gcd(32, channels))(x)
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1))(x)
        x = nn.GroupNorm(num_groups=math.gcd(32, self.filters))(x)
        return nn.relu(x)


class _MobileNet(nn.Module):
    """MobileNetV1 layout — depthwise-separable stacks."""

    num_classes: int
    width_multiplier: float = 1.0

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]

        def w(c):
            return max(8, int(c * self.width_multiplier))

        x = nn.Conv(w(32), (3, 3), strides=(2, 2), padding="SAME")(x)
        x = nn.relu(nn.GroupNorm(num_groups=math.gcd(32, w(32)))(x))
        plan = [
            (w(64), (1, 1)), (w(128), (2, 2)), (w(128), (1, 1)),
            (w(256), (2, 2)), (w(256), (1, 1)), (w(512), (2, 2)),
            *([(w(512), (1, 1))] * 5),
            (w(1024), (2, 2)), (w(1024), (1, 1)),
        ]
        for filters, strides in plan:
            x = _DepthwiseSeparable(filters=filters, strides=strides)(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class MobileNet(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        width_multiplier: float = 1.0,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.num_classes = num_classes
        self.width_multiplier = width_multiplier
        super().__init__(
            _MobileNet(
                num_classes=num_classes,
                width_multiplier=width_multiplier,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )
