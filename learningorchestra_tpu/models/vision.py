"""Vision models: MNIST CNN and ResNet.

The reference reaches ResNet50 through ``tensorflow.keras.applications``
in the model service (reference: microservices/model_image/model.py:92-162,
README demo pipelines at README.md:53).  Here they are Flax modules:

- convolutions in NHWC (TPU-native layout; XLA tiles convs onto the MXU);
- GroupNorm instead of BatchNorm — batch-statistics-free, so the module is
  a pure function of (params, x): no mutable state collections to thread
  through jit/shard_map, and normalization is independent of the
  data-parallel batch split (BatchNorm under DP needs cross-replica stats
  sync, a host of complexity the reference's Horovod path simply got wrong
  by using per-replica stats).
"""

from __future__ import annotations

import math
from typing import Sequence

from flax import linen as nn

from learningorchestra_tpu.ops.layers import remat_block
from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import NeuralEstimator

_MODULE = "learningorchestra_tpu.models.vision"


class _MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        # Accept (B, 784) flat or (B, 28, 28) or (B, 28, 28, 1).
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 28, 28, 1))
        elif x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class MnistCNN(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 10,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.num_classes = num_classes
        super().__init__(
            _MnistCNN(num_classes=num_classes),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


class _ResNetBlock(nn.Module):
    filters: int
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), self.strides, use_bias=False
            )(x)
            residual = nn.GroupNorm(num_groups=min(32, self.filters))(
                residual
            )
        return nn.relu(y + residual)


class _BottleneckBlock(nn.Module):
    filters: int
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters))(y)
        y = nn.relu(y)
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=False)(y)
        y = nn.GroupNorm(num_groups=min(32, 4 * self.filters))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                4 * self.filters, (1, 1), self.strides, use_bias=False
            )(x)
            residual = nn.GroupNorm(num_groups=min(32, 4 * self.filters))(
                residual
            )
        return nn.relu(y + residual)


class _ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: type
    num_classes: int = 1000
    width: int = 64
    # jax.checkpoint each residual block: activations rematerialize in
    # the backward pass — the batch-size headroom knob for conv nets,
    # where activation HBM (B x H x W x C per block) dominates params.
    remat: bool | str = False

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(self.width, (7, 7), (2, 2), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=min(32, self.width))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = remat_block(self.block, self.remat)
        idx = 0
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block_i in range(n_blocks):
                strides = (2, 2) if stage > 0 and block_i == 0 else (1, 1)
                # Explicit names pinned to the historical auto-names
                # (sequential across stages) so stored artifacts survive
                # toggling the memory knob — same convention as
                # BertEncoder's remat (models/text.py).
                x = block_cls(
                    self.width * (2**stage), strides=strides,
                    name=f"{self.block.__name__}_{idx}",
                )(x)
                idx += 1
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class ResNet18(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        learning_rate: float = 1e-3,
        seed: int = 0,
        remat: bool | str = False,
    ):
        self.num_classes = num_classes
        self.remat = remat
        super().__init__(
            _ResNet(
                stage_sizes=(2, 2, 2, 2),
                block=_ResNetBlock,
                num_classes=num_classes,
                remat=remat,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


@register(_MODULE)
class ResNet50(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        learning_rate: float = 1e-3,
        seed: int = 0,
        remat: bool | str = False,
    ):
        self.num_classes = num_classes
        self.remat = remat
        super().__init__(
            _ResNet(
                stage_sizes=(3, 4, 6, 3),
                block=_BottleneckBlock,
                num_classes=num_classes,
                remat=remat,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


# -- VGG ---------------------------------------------------------------------


class _VGG(nn.Module):
    """VGG-16 layout (Simonyan & Zisserman config D), GroupNorm'd."""

    num_classes: int
    stage_sizes: Sequence[int] = (2, 2, 3, 3, 3)
    widths: Sequence[int] = (64, 128, 256, 512, 512)

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        for blocks, width in zip(self.stage_sizes, self.widths):
            for _ in range(blocks):
                x = nn.Conv(width, (3, 3), padding="SAME")(x)
                x = nn.GroupNorm(num_groups=math.gcd(32, width))(x)
                x = nn.relu(x)
            # SAME-padded pooling: small inputs (e.g. 28x28 MNIST) must
            # not shrink to a zero-size axis (VALID would: 28->...->0,
            # making global average pooling return NaN).
            x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
        x = x.mean(axis=(1, 2))  # GAP replaces the 4096-wide FC stack
        x = nn.relu(nn.Dense(1024)(x))
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class VGG16(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.num_classes = num_classes
        super().__init__(
            _VGG(num_classes=num_classes),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


# -- MobileNet ---------------------------------------------------------------


class _DepthwiseSeparable(nn.Module):
    """Depthwise (feature_group_count=C) + pointwise conv pair."""

    filters: int
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        x = nn.Conv(
            channels, (3, 3), strides=self.strides, padding="SAME",
            feature_group_count=channels,
        )(x)
        # gcd: group count must DIVIDE the channel count, which
        # arbitrary width multipliers (0.75 -> 48 channels) break for a
        # fixed 32.
        x = nn.GroupNorm(num_groups=math.gcd(32, channels))(x)
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1))(x)
        x = nn.GroupNorm(num_groups=math.gcd(32, self.filters))(x)
        return nn.relu(x)


class _MobileNet(nn.Module):
    """MobileNetV1 layout — depthwise-separable stacks."""

    num_classes: int
    width_multiplier: float = 1.0

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]

        def w(c):
            return max(8, int(c * self.width_multiplier))

        x = nn.Conv(w(32), (3, 3), strides=(2, 2), padding="SAME")(x)
        x = nn.relu(nn.GroupNorm(num_groups=math.gcd(32, w(32)))(x))
        plan = [
            (w(64), (1, 1)), (w(128), (2, 2)), (w(128), (1, 1)),
            (w(256), (2, 2)), (w(256), (1, 1)), (w(512), (2, 2)),
            *([(w(512), (1, 1))] * 5),
            (w(1024), (2, 2)), (w(1024), (1, 1)),
        ]
        for filters, strides in plan:
            x = _DepthwiseSeparable(filters=filters, strides=strides)(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


@register(_MODULE)
class MobileNet(NeuralEstimator):
    def __init__(
        self,
        num_classes: int = 1000,
        width_multiplier: float = 1.0,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.num_classes = num_classes
        self.width_multiplier = width_multiplier
        super().__init__(
            _MobileNet(
                num_classes=num_classes,
                width_multiplier=width_multiplier,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )
