"""MLP classifier/regressor — the generic dense-net workhorse.

Stands in for both ``sklearn.neural_network.MLPClassifier`` and small
user-defined keras Sequential models the reference ships as JSON
(reference: microservices/binary_executor_image/binary_execution.py:248-251).
"""

from __future__ import annotations

from typing import Sequence

from flax import linen as nn

from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import NeuralEstimator

_MODULE = "learningorchestra_tpu.models.mlp"


class _MLP(nn.Module):
    features: tuple
    out_dim: int

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for width in self.features:
            x = nn.relu(nn.Dense(width)(x))
        return nn.Dense(self.out_dim)(x)


@register(_MODULE)
class MLPClassifier(NeuralEstimator):
    def __init__(
        self,
        hidden_layer_sizes: Sequence[int] = (128, 64),
        num_classes: int = 2,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.hidden_layer_sizes = tuple(hidden_layer_sizes)
        self.num_classes = num_classes
        super().__init__(
            _MLP(features=self.hidden_layer_sizes, out_dim=num_classes),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


@register(_MODULE)
class MLPRegressor(NeuralEstimator):
    def __init__(
        self,
        hidden_layer_sizes: Sequence[int] = (128, 64),
        out_dim: int = 1,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.hidden_layer_sizes = tuple(hidden_layer_sizes)
        self.out_dim = out_dim
        super().__init__(
            _MLP(features=self.hidden_layer_sizes, out_dim=out_dim),
            loss="mse",
            learning_rate=learning_rate,
            seed=seed,
        )
