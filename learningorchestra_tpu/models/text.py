"""Text models: LSTM sentiment classifier, Transformer encoder, BERT.

Covers the reference's demo NLP workloads (IMDb sentiment — README.md:53,
BASELINE.md config 3) and the BERT-base fine-tune target (BASELINE.md
config 4).  Inputs are int32 token-id matrices ``(batch, seq_len)``.

TPU notes: attention and the LSTM recurrence are expressed with
``nn.scan``/`lax` control flow (static trip counts, XLA-compilable); the
attention projections are feature-dim matmuls that shard cleanly on a
``tp`` mesh axis.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from learningorchestra_tpu.ops.layers import (
    MultiHeadSelfAttention,
    remat_block,
)
from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import NeuralEstimator

_MODULE = "learningorchestra_tpu.models.text"


class _LSTMClassifier(nn.Module):
    vocab_size: int
    embed_dim: int
    hidden_dim: int
    num_classes: int

    @nn.compact
    def __call__(self, tokens):
        tokens = tokens.astype(jnp.int32)
        x = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        lstm = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim))
        x = lstm(x)  # (B, T, H)
        # Mean-pool over non-pad positions (pad id 0).
        mask = (tokens != 0).astype(x.dtype)[..., None]
        pooled = (x * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
        return nn.Dense(self.num_classes)(pooled)


@register(_MODULE)
class LSTMClassifier(NeuralEstimator):
    def __init__(
        self,
        vocab_size: int = 20000,
        embed_dim: int = 128,
        hidden_dim: int = 128,
        num_classes: int = 2,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.hidden_dim = hidden_dim
        self.num_classes = num_classes
        super().__init__(
            _LSTMClassifier(
                vocab_size=vocab_size,
                embed_dim=embed_dim,
                hidden_dim=hidden_dim,
                num_classes=num_classes,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
            # The LSTM recurrence accumulates across T steps; bf16
            # cell-state drift is the classic failure mode, so this
            # family opts out of the zoo-wide mixed precision.
            compute_dtype="float32",
        )


def embed_tokens(tokens, vocab_size, hidden_dim, max_len, dtype,
                 positions=None):
    """Token + learned positional embedding (pad id 0 convention).

    A helper, not a submodule: called inside a ``@nn.compact``
    ``__call__`` the two ``nn.Embed`` layers auto-name in the CALLER's
    scope (``Embed_0``/``Embed_1``), so every transformer family —
    BERT, decoder LM, MoE, pipelined — shares one embedding definition
    without perturbing existing parameter trees.

    ``positions`` overrides the default ``arange`` positions — KV-cache
    decoding feeds one token at a time at its true buffer position.
    """
    if positions is None:
        positions = jnp.arange(tokens.shape[-1])[None, :]
    tok = nn.Embed(vocab_size, hidden_dim, dtype=dtype)(tokens)
    pos = nn.Embed(max_len, hidden_dim, dtype=dtype)(positions)
    return tok + pos


def cls_head(x, hidden_dim, num_classes):
    """[CLS]-pool position 0 through a tanh projection + classifier.
    Same call-site-scoping contract as :func:`embed_tokens`."""
    cls = jnp.tanh(nn.Dense(hidden_dim)(x[:, 0]))
    return nn.Dense(num_classes)(cls)


class TransformerBlock(nn.Module):
    """Pre-LN block over the framework's own attention layer: the Pallas
    flash kernel on TPU (ops/attention.py), jnp reference elsewhere —
    the reference system materialises full (T, T) scores inside wrapped
    keras models; this never does."""

    hidden_dim: int
    num_heads: int
    mlp_dim: int
    num_kv_heads: int | None = None  # grouped-query attention
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)
    use_flash: bool | None = None  # None = auto by backend
    causal: bool = False  # decoder blocks mask future positions
    window: int | None = None  # sliding-window attention (causal only)
    rope: bool = False  # rotary position embeddings
    decode: bool = False  # KV-cache autoregressive inference

    @nn.compact
    def __call__(self, x, key_mask=None):
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MultiHeadSelfAttention(
            num_heads=self.num_heads,
            qkv_features=self.hidden_dim,
            num_kv_heads=self.num_kv_heads,
            dtype=self.dtype,
            use_flash=self.use_flash,
            causal=self.causal,
            window=self.window,
            rope=self.rope,
            decode=self.decode,
        )(y, key_mask=key_mask)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden_dim, dtype=self.dtype)(y)
        return x + y


class BertEncoder(nn.Module):
    """BERT-style bidirectional transformer encoder (pre-LN)."""

    vocab_size: int = 30522
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)
    use_flash: bool | None = None
    # jax.checkpoint each block: activations rematerialize in the
    # backward pass — trades ~1 extra forward of FLOPs for O(layers)
    # less HBM, the standard long-sequence/large-batch headroom knob.
    remat: bool | str = False

    @nn.compact
    def __call__(self, tokens):
        tokens = tokens.astype(jnp.int32)
        x = embed_tokens(
            tokens, self.vocab_size, self.hidden_dim, self.max_len,
            self.dtype,
        )
        # Key-side padding mask (pad id 0).  Key-side masking is exact
        # for every non-pad query row; pad query rows produce values no
        # one reads — the [CLS] head pools position 0 only.
        pad_mask = tokens != 0  # (B, T)
        block_cls = remat_block(TransformerBlock, self.remat)
        for i in range(self.num_layers):
            # Explicit names keep the parameter tree identical whether
            # remat is on or off (auto-naming would differ:
            # CheckpointTransformerBlock_i vs TransformerBlock_i) AND
            # match the historical auto-names, so stored artifacts
            # survive toggling the memory knob.
            x = block_cls(
                hidden_dim=self.hidden_dim,
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                use_flash=self.use_flash,
                name=f"TransformerBlock_{i}",
            )(x, key_mask=pad_mask)
        return nn.LayerNorm(dtype=self.dtype)(x)


class _BertClassifier(nn.Module):
    encoder: BertEncoder
    num_classes: int

    @nn.compact
    def __call__(self, tokens):
        x = self.encoder(tokens)
        return cls_head(x, self.encoder.hidden_dim, self.num_classes)


@register(_MODULE)
class BertModel(NeuralEstimator):
    """BERT encoder + classification head (fine-tune surface).

    Defaults are BERT-base (L=12, H=768, A=12) per BASELINE.md config 4;
    shrink for tests with num_layers/hidden_dim kwargs.
    """

    def __init__(
        self,
        vocab_size: int = 30522,
        hidden_dim: int = 768,
        num_layers: int = 12,
        num_heads: int = 12,
        mlp_dim: int | None = None,
        max_len: int = 512,
        num_classes: int = 2,
        learning_rate: float = 2e-5,
        seed: int = 0,
        remat: bool | str = False,
        use_flash: bool | None = None,
    ):
        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim or hidden_dim * 4
        self.max_len = max_len
        self.num_classes = num_classes
        self.remat = remat
        encoder = BertEncoder(
            vocab_size=vocab_size,
            hidden_dim=hidden_dim,
            num_layers=num_layers,
            num_heads=num_heads,
            mlp_dim=self.mlp_dim,
            max_len=max_len,
            remat=remat,
            use_flash=use_flash,
        )
        super().__init__(
            _BertClassifier(encoder=encoder, num_classes=num_classes),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


@register(_MODULE)
class TransformerClassifier(BertModel):
    """Small-transformer alias with test-friendly defaults."""

    def __init__(
        self,
        vocab_size: int = 20000,
        hidden_dim: int = 128,
        num_layers: int = 2,
        num_heads: int = 4,
        max_len: int = 256,
        num_classes: int = 2,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        super().__init__(
            vocab_size=vocab_size,
            hidden_dim=hidden_dim,
            num_layers=num_layers,
            num_heads=num_heads,
            max_len=max_len,
            num_classes=num_classes,
            learning_rate=learning_rate,
            seed=seed,
        )


class _DecoderLM(nn.Module):
    """GPT-style causal transformer: pre-LN decoder blocks over the
    causal flash kernel, tied to a per-token LM head."""

    vocab_size: int
    hidden_dim: int
    num_layers: int
    num_heads: int
    mlp_dim: int
    max_len: int
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)
    use_flash: bool | None = None
    remat: bool | str = False
    decode: bool = False
    window: int | None = None  # sliding-window attention
    num_kv_heads: int | None = None  # grouped-query attention
    positional: str = "learned"  # 'learned' | 'rope'

    @nn.compact
    def __call__(self, tokens, positions=None, key_mask=None):
        tokens = tokens.astype(jnp.int32)
        if self.positional == "rope":
            # Rotary encodes position inside attention (ops/layers.py);
            # no learned table — the model extrapolates past max_len.
            x = nn.Embed(
                self.vocab_size, self.hidden_dim, dtype=self.dtype
            )(tokens)
        else:
            x = embed_tokens(
                tokens, self.vocab_size, self.hidden_dim, self.max_len,
                self.dtype, positions=positions,
            )
        if key_mask is None:
            key_mask = tokens != 0  # (B, T), pad id 0
        block_cls = remat_block(TransformerBlock, self.remat)
        for i in range(self.num_layers):
            x = block_cls(
                hidden_dim=self.hidden_dim,
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                num_kv_heads=self.num_kv_heads,
                dtype=self.dtype,
                use_flash=self.use_flash,
                causal=True,
                window=self.window,
                rope=self.positional == "rope",
                decode=self.decode,
                name=f"TransformerBlock_{i}",
            )(x, key_mask=key_mask)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab_size, dtype=self.dtype)(x)  # (B,T,V)


class GreedyDecodeMixin:
    """Autoregressive decoding for any estimator whose module maps
    token ids (B, T) to per-token vocab logits (B, T, V) and supports
    ``decode=True`` KV caching."""

    def generate(self, prompts, max_new_tokens: int = 32,
                 temperature: float | None = None,
                 top_k: int | None = None,
                 top_p: float | None = None, seed: int = 0):
        """Continuation of int32 prompts (B, T0): greedy by default,
        sampled with ``temperature`` (optionally ``top_k``-truncated
        and/or ``top_p`` nucleus-truncated — keep the smallest set of
        tokens whose probabilities sum past ``top_p``).

        KV-cache decoding: the whole generation (prompt prefill +
        continuation) is ONE jitted ``lax.scan`` over buffer positions
        — each step embeds a single token at its true position, attends
        against the per-layer K/V cache, and appends the next token.
        Cost per new token is O(T·H) instead of the O(T²·H) full
        re-forward of the naive loop, and the device round-trip count
        is 1, not T (the remote-TPU tunnel pays ~10-100 ms per round
        trip).  ``temperature`` is a runtime argument (no recompile);
        ``top_k`` changes the compiled graph and keys the fn cache."""
        import jax
        import numpy as np
        from jax import lax

        sample = temperature is not None and temperature > 0.0
        if top_k is not None and not sample:
            raise ValueError(
                "top_k requires a positive temperature (top_k without "
                "sampling silently degrades to greedy)"
            )
        if top_p is not None:
            if not sample:
                raise ValueError(
                    "top_p requires a positive temperature"
                )
            if not 0.0 < top_p <= 1.0:
                raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k == 1:
            # Deterministic by definition — use the greedy path (also
            # sidesteps tie-breaking drift vs argmax in low precision).
            sample, top_k = False, None
        prompts = np.asarray(prompts, dtype=np.int32)
        bsz, t0 = prompts.shape
        if t0 > self.max_len:
            # Without this, total < t0 below and the buffer scatter
            # fails with an opaque shape-broadcast trace error.
            raise ValueError(
                f"prompt length {t0} exceeds max_len={self.max_len}; "
                "truncate the prompt or build the model with a larger "
                "max_len"
            )
        total = min(self.max_len, t0 + max_new_tokens)

        # One (jitted scan, cache shapes) pair per prompt shape,
        # resolved through the CROSS-JOB compiled-program cache
        # (train/compile_cache): decode scans get fingerprints,
        # hit/miss stats, warm-start hints and the cache's bounded
        # eviction like every other program — two estimator instances
        # of one architecture share the executable (params enter as an
        # argument, never a baked-in constant), where the old private
        # per-instance LRU of 8 compiled one each, invisibly.
        from learningorchestra_tpu.train import compile_cache as cc

        shape_sig = (bsz, total, t0, sample, top_k, top_p is not None)
        cache_key = cc.program_key(
            "decode",
            module=cc.module_fingerprint(self.module),
            optimizer=None,
            loss="-",
            dtype="-",
            shapes=("decode", *shape_sig),
        )
        label = (
            f"decode:{type(self.module).__name__}:b{bsz}:t{total}"
        )

        def _build_decode():
            decode_mod = self.module.clone(decode=True)
            # Cache shapes via eval_shape (no real forward, no
            # throwaway params); the trained params drive the scan.
            cache_shapes = jax.eval_shape(
                decode_mod.init, jax.random.PRNGKey(0),
                jnp.zeros((bsz, total), jnp.int32),
            )["cache"]

            use_top_p = top_p is not None

            def decode(variables, cache, buf, temp, p_nucleus, key):
                def step(carry, i):
                    cache, buf = carry
                    tok = lax.dynamic_slice(buf, (0, i), (bsz, 1))
                    pos = jnp.full((bsz, 1), i, jnp.int32)
                    # Valid keys: non-pad tokens at positions already
                    # fed to the cache (prompt tokens beyond i are in
                    # the buffer but not yet cached).  Sliding-window
                    # models narrow this further inside the attention
                    # layer itself (ops/layers.py decode branch).
                    kmask = (jnp.arange(total)[None, :] <= i) \
                        & (buf != 0)
                    logits, mut = decode_mod.apply(
                        {**variables, "cache": cache}, tok,
                        positions=pos, key_mask=kmask,
                        mutable=["cache"],
                    )
                    step_logits = logits[:, 0].astype(jnp.float32)
                    if not sample:
                        nxt = jnp.argmax(step_logits, -1)
                    else:
                        # Never sample pad id 0: a mid-stream pad would
                        # be masked out of all later attention
                        # (buf != 0) and read as end-of-sequence.
                        step_logits = step_logits.at[:, 0].set(-jnp.inf)
                        if top_k is not None:
                            kth = lax.top_k(step_logits, top_k)[0][
                                ..., -1:]
                            step_logits = jnp.where(
                                step_logits < kth, -jnp.inf, step_logits
                            )
                        scaled = step_logits / temp
                        if use_top_p:
                            # Nucleus: drop tokens outside the smallest
                            # prefix (by descending prob) summing past
                            # p.  The threshold prob is found via sort+
                            # cumsum; p is a runtime arg (no recompile).
                            probs = jax.nn.softmax(scaled, -1)
                            srt = jnp.sort(probs, -1)[..., ::-1]
                            csum = jnp.cumsum(srt, -1)
                            cut = jnp.sum(
                                csum < p_nucleus, -1, keepdims=True
                            )
                            thresh = jnp.take_along_axis(srt, cut, -1)
                            scaled = jnp.where(
                                probs < thresh, -jnp.inf, scaled
                            )
                        nxt = jax.random.categorical(
                            jax.random.fold_in(key, i),
                            scaled, axis=-1,
                        )
                    nxt = nxt.astype(jnp.int32)
                    prev = lax.dynamic_slice(buf, (0, i + 1), (bsz, 1))
                    col = jnp.where(i + 1 >= t0, nxt[:, None], prev)
                    buf = lax.dynamic_update_slice(buf, col, (0, i + 1))
                    return (mut["cache"], buf), None

                (cache, buf), _ = lax.scan(
                    step, (cache, buf), jnp.arange(total - 1)
                )
                return buf

            return jax.jit(decode), cache_shapes

        decode, cache_shapes = cc.get_cache().get_or_build(
            cache_key, _build_decode, label=label
        )
        cache0 = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
        )
        buf0 = jnp.zeros((bsz, total), jnp.int32).at[:, :t0].set(
            jnp.asarray(prompts)
        )
        return np.asarray(decode(
            dict(self.params), cache0, buf0,
            jnp.float32(temperature if sample else 1.0),
            jnp.float32(top_p if top_p is not None else 1.0),
            jax.random.PRNGKey(seed),
        ))


@register(_MODULE)
class DecoderLM(GreedyDecodeMixin, NeuralEstimator):
    """Causal (decoder-only) language model — beyond-parity headroom:
    the reference has no attention at all (SURVEY §5.7); this pairs the
    causal Pallas flash kernel with the keras-fit surface.

    ``fit(x, y)`` with x = token ids (B, T) and y = next-token targets
    (B, T) (typically ``x[:, 1:]`` padded); the softmax_ce loss averages
    per-token over T (train/neural.py sequence handling).
    ``generate`` greedy-decodes continuations.
    """

    def __init__(
        self,
        vocab_size: int = 32000,
        hidden_dim: int = 256,
        num_layers: int = 4,
        num_heads: int = 8,
        mlp_dim: int | None = None,
        max_len: int = 1024,
        learning_rate: float = 3e-4,
        seed: int = 0,
        remat: bool | str = False,
        attention_window: int | None = None,
        num_kv_heads: int | None = None,
        positional: str = "learned",
    ):
        if positional not in ("learned", "rope"):
            raise ValueError(f"positional must be learned|rope, "
                             f"got {positional!r}")
        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim or hidden_dim * 4
        self.max_len = max_len
        self.remat = remat
        self.attention_window = attention_window
        self.num_kv_heads = num_kv_heads
        self.positional = positional
        super().__init__(
            _DecoderLM(
                vocab_size=vocab_size,
                hidden_dim=hidden_dim,
                num_layers=num_layers,
                num_heads=num_heads,
                mlp_dim=self.mlp_dim,
                max_len=max_len,
                remat=remat,
                window=attention_window,
                num_kv_heads=num_kv_heads,
                positional=positional,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )
