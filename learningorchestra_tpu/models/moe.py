"""Mixture-of-experts transformer family (expert parallelism).

Beyond-parity headroom: the reference zoo is dense keras/sklearn only
(reference: microservices/model_image/model.py:92-162 instantiates
``keras.applications`` classes; binary_executor_image ships dense keras
JSON) — it has no conditional-compute models.  These pair the routed
expert FFN (ops/moe.py) with the framework's attention stack: MoE
blocks interleave with dense blocks (GShard's every-other-layer
pattern), experts shard over the ``ep`` mesh axis, tokens reach them
via XLA-inserted all_to_all.

Scaling shape: parameters grow with ``num_experts`` while per-token
FLOPs stay ~constant (top-k of E experts run per token), so the model
family covers the "more capacity, same step time" axis the dense zoo
cannot.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from learningorchestra_tpu.models.text import (
    GreedyDecodeMixin,
    TransformerBlock,
    cls_head,
    embed_tokens,
)
from learningorchestra_tpu.ops.layers import MultiHeadSelfAttention
from learningorchestra_tpu.ops.moe import MoEMlp
from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import NeuralEstimator

_MODULE = "learningorchestra_tpu.models.moe"


class MoETransformerBlock(nn.Module):
    """Pre-LN transformer block whose FFN is a routed expert layer."""

    hidden_dim: int
    num_heads: int
    mlp_dim: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.5
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)
    use_flash: bool | None = None
    causal: bool = False
    window: int | None = None  # sliding-window attention (causal only)
    decode: bool = False

    @nn.compact
    def __call__(self, x, key_mask=None):
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MultiHeadSelfAttention(
            num_heads=self.num_heads,
            qkv_features=self.hidden_dim,
            dtype=self.dtype,
            use_flash=self.use_flash,
            causal=self.causal,
            window=self.window,
            decode=self.decode,
        )(y, key_mask=key_mask)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MoEMlp(
            num_experts=self.num_experts,
            hidden_dim=self.hidden_dim,
            mlp_dim=self.mlp_dim,
            top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            dtype=self.dtype,
        )(y)
        return x + y


class _MoETransformer(nn.Module):
    """Encoder/decoder trunk with MoE FFNs every ``moe_every`` blocks.

    ``head``: 'cls' pools position 0 through a tanh head (classifier),
    'lm' emits per-token vocab logits (causal LM).
    """

    vocab_size: int
    hidden_dim: int
    num_layers: int
    num_heads: int
    mlp_dim: int
    max_len: int
    num_experts: int
    num_classes: int
    head: str = "cls"
    moe_every: int = 2
    top_k: int = 2
    capacity_factor: float = 1.5
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)
    use_flash: bool | None = None
    decode: bool = False
    window: int | None = None  # sliding-window attention (lm head only)

    @nn.compact
    def __call__(self, tokens, positions=None, key_mask=None):
        tokens = tokens.astype(jnp.int32)
        causal = self.head == "lm"
        x = embed_tokens(
            tokens, self.vocab_size, self.hidden_dim, self.max_len,
            self.dtype, positions=positions,
        )
        if key_mask is None:
            key_mask = tokens != 0
        for i in range(self.num_layers):
            # MoE on the LAST block of each moe_every group so a
            # 1-layer net is still dense-first (router sees features).
            if (i + 1) % self.moe_every == 0:
                x = MoETransformerBlock(
                    hidden_dim=self.hidden_dim,
                    num_heads=self.num_heads,
                    mlp_dim=self.mlp_dim,
                    num_experts=self.num_experts,
                    top_k=self.top_k,
                    capacity_factor=self.capacity_factor,
                    dtype=self.dtype,
                    use_flash=self.use_flash,
                    causal=causal,
                    window=self.window if causal else None,
                    decode=self.decode,
                    name=f"MoEBlock_{i}",
                )(x, key_mask=key_mask)
            else:
                x = TransformerBlock(
                    hidden_dim=self.hidden_dim,
                    num_heads=self.num_heads,
                    mlp_dim=self.mlp_dim,
                    dtype=self.dtype,
                    use_flash=self.use_flash,
                    causal=causal,
                    window=self.window if causal else None,
                    decode=self.decode,
                    name=f"TransformerBlock_{i}",
                )(x, key_mask=key_mask)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if self.head == "lm":
            return nn.Dense(self.vocab_size, dtype=self.dtype)(x)
        return cls_head(x, self.hidden_dim, self.num_classes)


@register(_MODULE)
class MoETransformerClassifier(NeuralEstimator):
    """Sequence classifier with routed-expert FFNs."""

    def __init__(
        self,
        vocab_size: int = 20000,
        hidden_dim: int = 128,
        num_layers: int = 2,
        num_heads: int = 4,
        mlp_dim: int | None = None,
        max_len: int = 256,
        num_experts: int = 8,
        top_k: int = 2,
        capacity_factor: float = 1.5,
        moe_every: int = 2,
        num_classes: int = 2,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim or hidden_dim * 4
        self.max_len = max_len
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.moe_every = moe_every
        self.num_classes = num_classes
        super().__init__(
            _MoETransformer(
                vocab_size=vocab_size,
                hidden_dim=hidden_dim,
                num_layers=num_layers,
                num_heads=num_heads,
                mlp_dim=self.mlp_dim,
                max_len=max_len,
                num_experts=num_experts,
                num_classes=num_classes,
                head="cls",
                moe_every=moe_every,
                top_k=top_k,
                capacity_factor=capacity_factor,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )


@register(_MODULE)
class MoEDecoderLM(GreedyDecodeMixin, NeuralEstimator):
    """Causal LM with routed-expert FFNs (sparse GPT shape)."""

    def __init__(
        self,
        vocab_size: int = 32000,
        hidden_dim: int = 256,
        num_layers: int = 4,
        num_heads: int = 8,
        mlp_dim: int | None = None,
        max_len: int = 1024,
        num_experts: int = 8,
        top_k: int = 2,
        capacity_factor: float = 1.5,
        moe_every: int = 2,
        learning_rate: float = 3e-4,
        seed: int = 0,
        attention_window: int | None = None,
    ):
        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim or hidden_dim * 4
        self.max_len = max_len
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.moe_every = moe_every
        self.attention_window = attention_window
        super().__init__(
            _MoETransformer(
                vocab_size=vocab_size,
                hidden_dim=hidden_dim,
                num_layers=num_layers,
                num_heads=num_heads,
                mlp_dim=self.mlp_dim,
                max_len=max_len,
                num_experts=num_experts,
                num_classes=vocab_size,
                head="lm",
                moe_every=moe_every,
                top_k=top_k,
                capacity_factor=capacity_factor,
                window=attention_window,
            ),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )
