"""Long-context transformer — sequence-parallel attention over ``sp``.

The reference's sequence length is bounded by one Horovod worker's
``model.fit`` memory (SURVEY §5.7: no attention code, scaling = more
data-parallel replicas only).  This model family is the long-context
capability the TPU framework adds: attention runs as ring attention
(parallel/ring_attention.py) when a mesh with ``sp > 1`` is bound — each
device holds T/sp of the sequence and K/V blocks rotate over ICI — and
as vanilla attention otherwise, with an IDENTICAL parameter tree either
way (the mesh is runtime state, not architecture).

``DistributedTrainer`` binds its mesh automatically via ``bind_mesh``;
stored artifacts drop the mesh (meshes aren't serializable state) and
re-bind on the next distributed run.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh

from learningorchestra_tpu.parallel.ring_attention import RingSelfAttention
from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import NeuralEstimator

_MODULE = "learningorchestra_tpu.models.longcontext"


class _LongBlock(nn.Module):
    hidden_dim: int
    num_heads: int
    mlp_dim: int
    mesh: Mesh | None
    causal: bool
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)

    @nn.compact
    def __call__(self, x, kmask=None):
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = RingSelfAttention(
            num_heads=self.num_heads,
            mesh=self.mesh,
            causal=self.causal,
            dtype=self.dtype,
            name="attention",
        )(y, kmask=kmask)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden_dim, dtype=self.dtype)(y)
        return x + y


class _LongClassifier(nn.Module):
    vocab_size: int
    hidden_dim: int
    num_layers: int
    num_heads: int
    mlp_dim: int
    max_len: int
    num_classes: int
    mesh: Mesh | None
    causal: bool

    @nn.compact
    def __call__(self, tokens):
        tokens = tokens.astype(jnp.int32)
        seq = tokens.shape[1]
        x = nn.Embed(self.vocab_size, self.hidden_dim)(tokens)
        x = x + nn.Embed(self.max_len, self.hidden_dim)(
            jnp.arange(seq)[None, :]
        )
        kmask = tokens != 0
        for _ in range(self.num_layers):
            x = _LongBlock(
                hidden_dim=self.hidden_dim,
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                mesh=self.mesh,
                causal=self.causal,
            )(x, kmask=kmask)
        x = nn.LayerNorm()(x)
        # Mean-pool valid positions (sequence may be sharded; the mean is
        # a plain reduction XLA handles across shards).
        m = kmask.astype(x.dtype)[..., None]
        pooled = (x * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
        return nn.Dense(self.num_classes)(pooled)


@register(_MODULE)
class LongContextTransformer(NeuralEstimator):
    """Sequence-parallel transformer classifier.

    Train single-device like any estimator, or through
    ``DistributedTrainer(..., shard_sequence=True)`` on a mesh with
    ``sp > 1`` for sequences that don't fit one device.
    """

    def __init__(
        self,
        vocab_size: int = 32000,
        hidden_dim: int = 256,
        num_layers: int = 4,
        num_heads: int = 8,
        mlp_dim: int | None = None,
        max_len: int = 65536,
        num_classes: int = 2,
        causal: bool = False,
        learning_rate: float = 1e-3,
        seed: int = 0,
    ):
        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim or hidden_dim * 4
        self.max_len = max_len
        self.num_classes = num_classes
        self.causal = causal
        super().__init__(
            self._make_module(mesh=None),
            loss="softmax_ce",
            learning_rate=learning_rate,
            seed=seed,
        )

    def _make_module(self, mesh: Mesh | None) -> _LongClassifier:
        return _LongClassifier(
            vocab_size=self.vocab_size,
            hidden_dim=self.hidden_dim,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            mlp_dim=self.mlp_dim,
            max_len=self.max_len,
            num_classes=self.num_classes,
            mesh=mesh,
            causal=self.causal,
        )

    def _init_params(self, x0) -> None:
        """Initialize through the vanilla-attention module: init sees a
        single example, which need not divide the mesh's data axes, and
        both attention paths share one parameter tree."""
        if getattr(self.module, "mesh", None) is None:
            return super()._init_params(x0)
        import jax

        rng = jax.random.PRNGKey(self.seed)
        self.params = self._make_module(mesh=None).init(rng, x0)
        self.opt_state = self.optimizer.init(self.params)

    def bind_mesh(self, mesh: Mesh | None) -> None:
        """Swap the attention implementation (ring ⇄ vanilla) for the
        given mesh.  Parameters are untouched — both paths share one
        parameter tree — but jitted closures are invalidated."""
        self.module = self._make_module(mesh)
        self._step_fn = None
        self._eval_fn = None
        self._apply_fn = None
        # Per-bucket applies are memoized by row count ONLY — a module
        # swap must drop them or a stale ring/vanilla apply would serve.
        self._apply_fns = {}
        self._device_epoch = None
        self._device_epoch_key = None

    def __getstate__(self):
        d = super().__getstate__()
        # Meshes hold device handles — never serialize them.
        d["module"] = self._make_module(mesh=None)
        return d
