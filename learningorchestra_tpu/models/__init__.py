"""Flax model zoo.

Replaces the neural-network surface the reference reaches through
``tensorflow.keras`` — both user-defined keras models shipped as JSON
(reference: microservices/binary_executor_image/binary_execution.py:248-251)
and pre-trained ``keras.applications`` classes instantiated by the model
service (model_image/model.py:92-162).  Every zoo entry is a Flax module
wrapped in a :class:`~learningorchestra_tpu.train.neural.NeuralEstimator`,
which provides the keras-like ``fit/evaluate/predict`` methods the executor
layer drives by reflection.
"""

from learningorchestra_tpu.models.mlp import MLPClassifier, MLPRegressor
from learningorchestra_tpu.models.vision import (
    MnistCNN,
    MobileNet,
    ResNet18,
    ResNet50,
    VGG16,
)
from learningorchestra_tpu.models.text import (
    DecoderLM,
    LSTMClassifier,
    TransformerClassifier,
    BertModel,
)
from learningorchestra_tpu.models.longcontext import LongContextTransformer
from learningorchestra_tpu.models.moe import (
    MoEDecoderLM,
    MoETransformerClassifier,
)

__all__ = [
    "MLPClassifier",
    "MLPRegressor",
    "MnistCNN",
    "ResNet18",
    "ResNet50",
    "VGG16",
    "MobileNet",
    "LSTMClassifier",
    "TransformerClassifier",
    "BertModel",
    "DecoderLM",
    "LongContextTransformer",
    "MoEDecoderLM",
    "MoETransformerClassifier",
]
