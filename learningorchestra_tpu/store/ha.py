"""Automatic store failover — the reference's replica-set election.

The reference deploys MongoDB as a 3-node replica set whose secondaries
take over automatically when the primary dies (reference:
docker-compose.yml:42-90 — ``replSetInitiate`` + driver re-discovery).
Here the store is embedded in the API server process, so HA is a
process-pair story instead of a database protocol:

- The PRIMARY is an ordinary ``serve`` process over its store directory.
- A STANDBY process (``python -m learningorchestra_tpu standby``) runs a
  :class:`StandbyMonitor`: it ships the primary's WALs continuously
  (:class:`~learningorchestra_tpu.store.replica.WalReplica`) — through
  the filesystem when it shares a mount with the primary, or over the
  primary's ``/replication`` HTTP routes when it runs on its own host
  with its own disk (the mongo-secondary topology; pass the primary's
  ADDRESS instead of a store path).  It probes the primary's
  ``/health`` route every ``check_interval`` seconds, and after
  ``max_misses`` consecutive failed probes performs the election a
  Mongo secondary would win:

  1. **final sync** — ship every complete WAL record still readable
     from the primary.  On a shared filesystem a kill -9'd primary
     loses NO acknowledged writes: they are all in its WALs, and only
     the torn tail — which the primary's own restart recovery would
     also discard — is withheld.  Over the network the loss window is
     the replication lag, exactly Mongo's w:1 rollback window.
  2. **fence** — mark the old primary dead: write a ``.fenced`` marker
     into its store directory (filesystem transport) or POST it to the
     primary's ``/replication/fence`` route (network transport, lands
     only if the "dead" primary is actually alive behind a partition —
     which is precisely when the fence matters).  A fenced primary
     refuses to serve; a RUNNING one self-demotes (api/server.py).
  3. **epoch bump** — the promoted replica's ``.epoch`` becomes the
     primary's last-known epoch + 1 (mongo's election term).  A
     restarted old primary configured with ``LO_HA_PEER`` asks its
     peer's ``/replication/status`` and refuses to serve when the peer
     holds a HIGHER epoch — split-brain protection that needs no
     shared disk.
  4. **promote** — the replica directory is a valid store directory, so
     the standby opens it writable and starts the FULL API server on
     its own port: the new primary.  A ``.promoted`` record in the
     replica root makes standby restarts resume as primary instead of
     re-syncing from (and being rolled back by) the dead primary.

Clients pass ``failover=`` to :class:`~learningorchestra_tpu.client.Context`
and retry once against the standby address on connection failure — the
driver-side half of Mongo's automatic server re-discovery.
"""

from __future__ import annotations

import json
import socket
import time
import urllib.error
import urllib.request
from datetime import datetime, timezone
from pathlib import Path

from learningorchestra_tpu import faults
from learningorchestra_tpu.log import get_logger
from learningorchestra_tpu.store.replica import (
    FENCE_FILE,
    WalReplica,
    make_transport,
    read_epoch,
    write_epoch,
)

__all__ = [
    "FENCE_FILE",
    "PROMOTED_FILE",
    "StandbyMonitor",
    "is_fenced",
    "peer_status",
    "read_epoch",
    "run_standby",
    "write_epoch",
]

log = get_logger("ha")  # get_logger prepends the "lo." namespace

#: Record a promotion writes into its OWN replica root — the standby's
#: durable memory that it became primary (the fence marker lives on the
#: OLD primary's disk, which a network standby cannot read).
PROMOTED_FILE = ".promoted"


def is_fenced(store_root: str | Path) -> dict | None:
    """Return the fence record if ``store_root`` was fenced by a
    promotion, else None.  ``serve`` checks this at startup so a
    supervisor-restarted old primary exits instead of split-braining."""
    path = Path(store_root) / FENCE_FILE
    try:
        return json.loads(path.read_text())
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        # Unreadable ≠ absent: a marker we cannot parse (torn write,
        # permission change) still means SOMEONE fenced this store —
        # fail safe and refuse to serve rather than split-brain.
        return {"reason": "unreadable fence marker"}


def promotion_record(replica_root: str | Path) -> dict | None:
    """The ``.promoted`` record if this replica already became primary."""
    path = Path(replica_root) / PROMOTED_FILE
    try:
        return json.loads(path.read_text())
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        return {"reason": "unreadable promotion record"}


def peer_status(peer_addr: str, *, timeout: float = 2.0,
                prefix: str = "/api/learningOrchestra/v1") -> dict | None:
    """One ``/replication/status`` round-trip to the HA peer.

    Returns the peer's ``{"role", "epoch", ...}`` record, or None when
    the peer is unreachable.  A MONITORING standby answers this route
    too (``role="standby"``, _start_standby_status) — a non-None
    record is NOT proof the peer promoted; check ``role``."""
    url = f"http://{peer_addr}{prefix}/replication/status"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, OSError, ValueError):
        return None


class StandbyMonitor:
    """Ship WALs from a primary and decide when to take over.

    ``primary_store`` may be a path (filesystem shipping over a shared
    mount) or ``None`` — in which case WALs ship over HTTP from
    ``primary_addr`` and the node pair needs no shared storage at all.
    """

    def __init__(
        self,
        primary_addr: str,
        primary_store: str | Path | None,
        replica_root: str | Path,
        *,
        check_interval: float = 0.5,
        max_misses: int = 4,
        probe_timeout: float = 1.0,
        new_primary_addr: str = "",
        require_first_contact: bool = True,
    ):
        self.primary_addr = primary_addr
        self.primary_store = (
            Path(primary_store) if primary_store is not None else None
        )
        transport = make_transport(
            str(primary_store) if primary_store is not None
            else primary_addr
        )
        self.replica = WalReplica(transport, replica_root)
        self.check_interval = check_interval
        self.max_misses = max_misses
        self.probe_timeout = probe_timeout
        self.new_primary_addr = new_primary_addr
        # Never elect over a primary we have never reached: a standby
        # that boots alongside a slow-starting primary (cold `compose
        # up`: jax imports alone exceed interval*misses) must wait, not
        # fence a healthy node out of existence.  An unreachable-from-
        # birth primary is indistinguishable from a standby pointed at
        # the wrong address — takeover there is never safe.
        self.require_first_contact = require_first_contact
        self.saw_primary = False
        self.misses = 0
        # The primary's election term, refreshed on every successful
        # sync — promotion bumps from the LAST KNOWN value because the
        # primary is usually unreachable by then.
        self.primary_epoch = 0
        # Last successful sync, for the pre-promotion status endpoint
        # (mongo's printSecondaryReplicationInfo role): read cross-
        # thread by _StandbyStatusServer — plain floats/ints only.
        self.last_sync_at = 0.0
        self.last_sync_bytes = 0

    def probe(self) -> bool:
        """One /health round-trip: is the primary PROCESS alive?

        ANY HTTP response — including the gateway's 503 backpressure
        when ``max_inflight`` is saturated — proves a live process
        still serving its store; only connection-level failure
        (refused/reset/timeout) counts as a miss.  Promoting over a
        merely-saturated primary would split-brain the cluster.
        """
        url = (
            f"http://{self.primary_addr}/api/learningOrchestra/v1/health"
        )
        try:
            with urllib.request.urlopen(
                url, timeout=self.probe_timeout
            ):
                return True
        except urllib.error.HTTPError:
            return True  # it answered: alive
        except (urllib.error.URLError, OSError, TimeoutError):
            return False

    def step(self) -> bool:
        """One monitor iteration: sync, probe, count misses.

        Returns True when the takeover threshold is reached.  Sync
        happens BEFORE the probe so the replication lag at the moment
        of a detected death is one interval, not two.
        """
        try:
            shipped = self.replica.sync()
            self.last_sync_at = time.time()
            self.last_sync_bytes = sum(shipped.values())
            # Never let the cached epoch REGRESS: a degraded primary
            # whose store dir unmounted can answer a listing with
            # epoch 0 (read_epoch swallows the OSError); promoting
            # from a regressed value would mint an epoch BELOW the
            # real history and the split-brain protection would wave
            # the stale primary back in.
            self.primary_epoch = max(
                self.primary_epoch, self.replica.transport.epoch()
            )
        except OSError as exc:
            # A vanishing primary directory is itself a failure signal;
            # keep probing — the health check decides.  Nothing is
            # deleted on this path: sync() raised before touching the
            # replica's WALs.
            log.warning(f"standby sync error: {exc}")
        if self.probe():
            if not self.saw_primary:
                log.info(f"primary {self.primary_addr} reached — "
                         "takeover arming enabled")
            self.saw_primary = True
            self.misses = 0
            return False
        if self.require_first_contact and not self.saw_primary:
            # Startup grace: the primary may still be booting.
            self.misses += 1
            if self.misses % 30 == 0:
                log.warning(
                    f"primary {self.primary_addr} still unreached "
                    f"after {self.misses} probes; standing by "
                    "(takeover requires first contact)"
                )
            return False
        self.misses += 1
        log.warning(
            f"primary {self.primary_addr} missed health check "
            f"({self.misses}/{self.max_misses})"
        )
        return self.misses >= self.max_misses

    def run_until_takeover(self) -> Path:
        """Block until the primary is declared dead, then promote.

        Returns the replica root, now fenced-off from the old primary
        and ready to open as the new system-of-record.
        """
        while not self.step():
            time.sleep(self.check_interval)
        return self.promote()

    def promote(self) -> Path:
        """Final-sync, bump the epoch, fence the old primary, hand
        over the directory.  The final sync never deletes replicated
        data (``allow_drops=False``) — a dying primary that presents
        an empty or missing store must not take the replica with it."""
        # Chaos probe: an injected `error` models the standby dying at
        # the election moment — promotion is idempotent (the epoch
        # bump and fence land only on success), so a supervisor
        # restart re-promotes cleanly; the kill-9 recovery drills arm
        # seeded schedules here.
        faults.hit("store.ha.failover")
        try:
            shipped = self.replica.sync(allow_drops=False)
            self.primary_epoch = max(
                self.primary_epoch, self.replica.transport.epoch()
            )
        except OSError:
            shipped = {}
        new_epoch = self.primary_epoch + 1
        write_epoch(self.replica.replica_root, new_epoch)
        record = {
            "promoted_to": self.new_primary_addr,
            "replica_root": str(self.replica.replica_root),
            "old_primary": self.primary_addr,
            "epoch": new_epoch,
            "at": datetime.now(timezone.utc).isoformat(),
        }
        # Durable local memory FIRST: if we crash between here and
        # serving, the supervisor restart must resume as primary, not
        # re-sync from (and get rolled back by) the dead primary.
        (self.replica.replica_root / PROMOTED_FILE).write_text(
            json.dumps(record)
        )
        self._write_fence(record)
        total = sum(shipped.values())
        log.info(
            f"promoted replica {self.replica.replica_root} "
            f"(epoch {new_epoch}, final sync shipped {total} bytes)"
        )
        return self.replica.replica_root

    def _write_fence(self, record: dict) -> None:
        try:
            self.replica.transport.fence(record)
        except OSError as exc:
            # The primary may be gone entirely — promotion must still
            # proceed.  Over the filesystem this is best-effort
            # protection; over the network the epoch comparison
            # (serve()'s peer check) covers the restarted primary.
            log.warning(f"could not fence old primary: {exc}")


def _start_standby_status(host: str, port: int,
                          monitor: StandbyMonitor):
    """Observability for a MONITORING standby (mongo's
    ``rs.printSecondaryReplicationInfo()`` role): before promotion the
    standby binds its future API port and serves exactly one route —
    ``GET …/replication/status`` → ``role=standby`` + sync freshness —
    answering every other request 503 ("not promoted").  The 503 is
    part of the failover protocol: the client treats it as "pair
    alive, election hasn't happened" and does NOT repoint
    (client.py request()), unlike any other HTTP answer.  Binding
    early also reserves the port, so a colliding service fails at
    bring-up instead of at election time.

    Returns the server (shut it down before the promoted APIServer
    binds), or None when the port cannot be bound — status is an
    extra, never a reason to refuse to stand by.
    """
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.rstrip("/").endswith("/replication/status"):
                body = json.dumps({
                    "role": "standby",
                    "primary": monitor.primary_addr,
                    "epoch": monitor.primary_epoch,
                    "saw_primary": monitor.saw_primary,
                    "misses": monitor.misses,
                    "last_sync_at": monitor.last_sync_at,
                    "last_sync_bytes": monitor.last_sync_bytes,
                }).encode()
                self._send(200, body)
            else:
                self._not_promoted()

        def _not_promoted(self):
            self._send(503, json.dumps(
                {"error": "standby: monitoring, not promoted"}
            ).encode())

        do_POST = do_PATCH = do_DELETE = do_PUT = _not_promoted

        def _send(self, code: int, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # noqa: D102 — quiet
            pass

    try:
        srv = http.server.ThreadingHTTPServer((host, port), Handler)
    except OSError as exc:
        log.warning(
            f"standby status endpoint could not bind {host}:{port} "
            f"({exc}) — monitoring without it"
        )
        return None
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def run_standby(
    primary_addr: str,
    primary_store: str | Path | None,
    replica_root: str | Path,
    port: int,
    *,
    check_interval: float = 0.5,
    max_misses: int = 4,
    host: str = "0.0.0.0",
) -> None:
    """The ``standby`` CLI role: monitor, then become the API server.

    Blocks forever: first in the monitor loop, then — after promotion —
    serving the full REST API over the promoted directory on ``port``.
    """
    # Pay the heavy server import while the primary is still healthy —
    # takeover latency must be probe-bound, not import-bound.
    from learningorchestra_tpu.api.server import APIServer
    from learningorchestra_tpu.config import Config, set_config

    # The advertised address lands in the fence record and the fenced
    # primary's operator guidance — a bind-all wildcard is useless
    # there, so substitute the host's name.
    advertised_host = (
        socket.gethostname() if host in ("0.0.0.0", "::") else host
    )

    def become_primary(promoted: Path) -> None:
        from learningorchestra_tpu.api.server import _peer_supersedes

        config = Config.from_env()
        config.store.root = str(promoted)
        config.api.port = port
        # The dead primary is now OUR peer: if it resurrects with a
        # higher epoch (it re-promoted over us during a partition), we
        # must stand down — the fence watch polls it.
        config.ha.peer = primary_addr
        set_config(config)  # services resolving get_config() must agree
        # Startup epoch check, same as serve(): a RESUMING promoted
        # replica may itself have been superseded while down (the
        # partner auto-rejoined our replica and re-promoted over us) —
        # serving would split-brain until the fence watch's first
        # peer poll.  A superseded resume writes its fence and exits
        # cleanly; the supervisor's next restart refuses immediately.
        fence = _peer_supersedes(promoted, primary_addr)
        if fence is not None:
            print(
                "promoted replica is superseded by "
                f"{fence.get('promoted_to')!r} (higher election "
                "epoch) — refusing to resume as primary.",
                flush=True,
            )
            return
        APIServer(config).serve_forever(host=host, port=port)

    # Standby RESTART after promotion: the replica dir's own record is
    # authoritative (a network standby cannot read the old primary's
    # fence marker).  The replica dir is the current system of record —
    # syncing from the dead primary again would classify our own
    # post-failover WAL growth as a rewrite and roll it back.  A FENCE
    # in the replica root overrides the promotion record: someone
    # re-promoted over this store since.
    if promotion_record(replica_root) is not None:
        fence = is_fenced(replica_root)
        if fence is not None:
            # Clean exit (code 0): a supervisor's restart-on-failure
            # loop must END here, not crash-loop — same contract as
            # serve()'s fenced refusal.
            print(
                f"promoted replica {replica_root} was later fenced in "
                f"favor of {fence.get('promoted_to')!r} — superseded; "
                "refusing to resume as primary.",
                flush=True,
            )
            return
        log.info(
            "store already promoted to this replica — resuming as "
            "primary without re-sync"
        )
        become_primary(Path(replica_root))
        return

    if primary_store is not None:
        fence = is_fenced(primary_store)
        if fence is not None:
            # If WE fenced it (same replica root), this is a pre-
            # ``.promoted``-era restart after promotion: resume as
            # primary.  Otherwise someone ELSE is primary now.
            if Path(fence.get("replica_root", "")).resolve() == (
                Path(replica_root).resolve()
            ):
                log.info(
                    "store already promoted to this replica — resuming "
                    "as primary without re-sync"
                )
                become_primary(Path(replica_root))
                return
            raise SystemExit(
                f"{primary_store} is fenced in favor of "
                f"{fence.get('replica_root')!r} (promoted_to="
                f"{fence.get('promoted_to')!r}) — refusing to stand by "
                "for a dead primary; re-point --primary/--primary-store "
                "at the current one."
            )

    monitor = StandbyMonitor(
        primary_addr,
        primary_store,
        replica_root,
        check_interval=check_interval,
        max_misses=max_misses,
        new_primary_addr=f"{advertised_host}:{port}",
    )
    log.info(
        f"standby shipping {primary_store or primary_addr} -> "
        f"{replica_root} via {monitor.replica.transport!r}, "
        f"watching http://{primary_addr}/health"
    )
    status_srv = _start_standby_status(host, port, monitor)
    try:
        promoted = monitor.run_until_takeover()
    finally:
        # Free the port for the promoted APIServer (and on an
        # exception, for whatever supervises this role).
        if status_srv is not None:
            status_srv.shutdown()
            status_srv.server_close()
    become_primary(promoted)
