"""Automatic store failover — the reference's replica-set election.

The reference deploys MongoDB as a 3-node replica set whose secondaries
take over automatically when the primary dies (reference:
docker-compose.yml:42-90 — ``replSetInitiate`` + driver re-discovery).
Here the store is embedded in the API server process, so HA is a
process-pair story instead of a database protocol:

- The PRIMARY is an ordinary ``serve`` process over its store directory.
- A STANDBY process (``python -m learningorchestra_tpu standby``) runs a
  :class:`StandbyMonitor`: it ships the primary's WALs continuously
  (:class:`~learningorchestra_tpu.store.replica.WalReplica`), probes the
  primary's ``/health`` route every ``check_interval`` seconds, and
  after ``max_misses`` consecutive failed probes performs the election
  a Mongo secondary would win:

  1. **final sync** — ship every complete WAL record still readable from
     the primary's directory.  On a shared filesystem (the local
     deployment) a kill -9'd primary loses NO acknowledged writes: they
     are all in its WALs, and only the torn tail — which the primary's
     own restart recovery would also discard — is withheld.  Across
     hosts the loss window is the replication lag, exactly Mongo's
     w:1 rollback window.
  2. **fence** — write a ``.fenced`` marker into the old primary's store
     directory.  A supervised restart of the old primary sees the marker
     and refuses to serve (clean exit), preventing the split-brain a
     revived Mongo primary avoids via election terms.
  3. **promote** — the replica directory is a valid store directory, so
     the standby opens it writable and starts the FULL API server on its
     own port: the new primary.

Clients pass ``failover=`` to :class:`~learningorchestra_tpu.client.Context`
and retry once against the standby address on connection failure — the
driver-side half of Mongo's automatic server re-discovery.
"""

from __future__ import annotations

import json
import socket
import time
import urllib.error
import urllib.request
from datetime import datetime, timezone
from pathlib import Path

from learningorchestra_tpu.log import get_logger
from learningorchestra_tpu.store.replica import WalReplica

log = get_logger("ha")  # get_logger prepends the "lo." namespace

#: Marker file a promotion writes into the OLD primary's store dir.
FENCE_FILE = ".fenced"


def is_fenced(store_root: str | Path) -> dict | None:
    """Return the fence record if ``store_root`` was fenced by a
    promotion, else None.  ``serve`` checks this at startup so a
    supervisor-restarted old primary exits instead of split-braining."""
    path = Path(store_root) / FENCE_FILE
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except ValueError:
        return {"reason": "unreadable fence marker"}


class StandbyMonitor:
    """Ship WALs from a primary and decide when to take over."""

    def __init__(
        self,
        primary_addr: str,
        primary_store: str | Path,
        replica_root: str | Path,
        *,
        check_interval: float = 0.5,
        max_misses: int = 4,
        probe_timeout: float = 1.0,
        new_primary_addr: str = "",
        require_first_contact: bool = True,
    ):
        self.primary_addr = primary_addr
        self.primary_store = Path(primary_store)
        self.replica = WalReplica(primary_store, replica_root)
        self.check_interval = check_interval
        self.max_misses = max_misses
        self.probe_timeout = probe_timeout
        self.new_primary_addr = new_primary_addr
        # Never elect over a primary we have never reached: a standby
        # that boots alongside a slow-starting primary (cold `compose
        # up`: jax imports alone exceed interval*misses) must wait, not
        # fence a healthy node out of existence.  An unreachable-from-
        # birth primary is indistinguishable from a standby pointed at
        # the wrong address — takeover there is never safe.
        self.require_first_contact = require_first_contact
        self.saw_primary = False
        self.misses = 0

    def probe(self) -> bool:
        """One /health round-trip: is the primary PROCESS alive?

        ANY HTTP response — including the gateway's 503 backpressure
        when ``max_inflight`` is saturated — proves a live process
        still serving its store; only connection-level failure
        (refused/reset/timeout) counts as a miss.  Promoting over a
        merely-saturated primary would split-brain the cluster.
        """
        url = (
            f"http://{self.primary_addr}/api/learningOrchestra/v1/health"
        )
        try:
            with urllib.request.urlopen(
                url, timeout=self.probe_timeout
            ):
                return True
        except urllib.error.HTTPError:
            return True  # it answered: alive
        except (urllib.error.URLError, OSError, TimeoutError):
            return False

    def step(self) -> bool:
        """One monitor iteration: sync, probe, count misses.

        Returns True when the takeover threshold is reached.  Sync
        happens BEFORE the probe so the replication lag at the moment
        of a detected death is one interval, not two.
        """
        try:
            self.replica.sync()
        except OSError as exc:
            # A vanishing primary directory is itself a failure signal;
            # keep probing — the health check decides.
            log.warning(f"standby sync error: {exc}")
        if self.probe():
            if not self.saw_primary:
                log.info(f"primary {self.primary_addr} reached — "
                         "takeover arming enabled")
            self.saw_primary = True
            self.misses = 0
            return False
        if self.require_first_contact and not self.saw_primary:
            # Startup grace: the primary may still be booting.
            self.misses += 1
            if self.misses % 30 == 0:
                log.warning(
                    f"primary {self.primary_addr} still unreached "
                    f"after {self.misses} probes; standing by "
                    "(takeover requires first contact)"
                )
            return False
        self.misses += 1
        log.warning(
            f"primary {self.primary_addr} missed health check "
            f"({self.misses}/{self.max_misses})"
        )
        return self.misses >= self.max_misses

    def run_until_takeover(self) -> Path:
        """Block until the primary is declared dead, then promote.

        Returns the replica root, now fenced-off from the old primary
        and ready to open as the new system-of-record.
        """
        while not self.step():
            time.sleep(self.check_interval)
        return self.promote()

    def promote(self) -> Path:
        """Final-sync, fence the old primary, hand over the directory."""
        try:
            shipped = self.replica.sync()
        except OSError:
            shipped = {}
        self._write_fence()
        total = sum(shipped.values())
        log.info(
            f"promoted replica {self.replica.replica_root} "
            f"(final sync shipped {total} bytes)"
        )
        return self.replica.replica_root

    def _write_fence(self) -> None:
        record = {
            "promoted_to": self.new_primary_addr,
            "replica_root": str(self.replica.replica_root),
            "at": datetime.now(timezone.utc).isoformat(),
        }
        try:
            self.primary_store.mkdir(parents=True, exist_ok=True)
            fence = self.primary_store / FENCE_FILE
            fence.write_text(json.dumps(record))
        except OSError as exc:
            # The primary's disk may be gone entirely — promotion must
            # still proceed; the fence is best-effort protection for the
            # shared-filesystem deployment where a restart CAN race us.
            log.warning(f"could not fence old primary: {exc}")


def run_standby(
    primary_addr: str,
    primary_store: str | Path,
    replica_root: str | Path,
    port: int,
    *,
    check_interval: float = 0.5,
    max_misses: int = 4,
    host: str = "0.0.0.0",
) -> None:
    """The ``standby`` CLI role: monitor, then become the API server.

    Blocks forever: first in the monitor loop, then — after promotion —
    serving the full REST API over the promoted directory on ``port``.
    """
    # Pay the heavy server import while the primary is still healthy —
    # takeover latency must be probe-bound, not import-bound.
    from learningorchestra_tpu.api.server import APIServer
    from learningorchestra_tpu.config import Config, set_config

    # The advertised address lands in the fence record and the fenced
    # primary's operator guidance — a bind-all wildcard is useless
    # there, so substitute the host's name.
    advertised_host = (
        socket.gethostname() if host in ("0.0.0.0", "::") else host
    )

    def become_primary(promoted: Path) -> None:
        config = Config.from_env()
        config.store.root = str(promoted)
        config.api.port = port
        set_config(config)  # services resolving get_config() must agree
        APIServer(config).serve_forever(host=host, port=port)

    fence = is_fenced(primary_store)
    if fence is not None:
        # The old primary is already fenced.  If WE fenced it (same
        # replica root), this is a standby RESTART after promotion: the
        # replica dir is the current system of record — syncing from
        # the dead primary again would classify our own post-failover
        # WAL growth as a rewrite and roll it back.  Serve immediately.
        if Path(fence.get("replica_root", "")).resolve() == (
            Path(replica_root).resolve()
        ):
            log.info(
                "store already promoted to this replica — resuming as "
                "primary without re-sync"
            )
            become_primary(Path(replica_root))
            return
        raise SystemExit(
            f"{primary_store} is fenced in favor of "
            f"{fence.get('replica_root')!r} (promoted_to="
            f"{fence.get('promoted_to')!r}) — refusing to stand by for "
            "a dead primary; re-point --primary/--primary-store at the "
            "current one."
        )

    monitor = StandbyMonitor(
        primary_addr,
        primary_store,
        replica_root,
        check_interval=check_interval,
        max_misses=max_misses,
        new_primary_addr=f"{advertised_host}:{port}",
    )
    log.info(
        f"standby shipping {primary_store} -> {replica_root}, "
        f"watching http://{primary_addr}/health"
    )
    become_primary(monitor.run_until_takeover())
