"""Embedded, thread-safe, WAL-backed document store.

System-of-record for every artifact, replacing the reference's MongoDB 3.6
replica set (reference: docker-compose.yml:42-90).  The API surface is the
subset of Mongo the reference actually uses:

- ``insert_one`` / ``insert_many`` with auto-incremented integer ``_id``
  (the reference allocates IDs read-then-insert, which races —
  binary_executor_image/utils.py:116-139; here allocation is atomic);
- ``find(query, sort, skip, limit)`` with equality / ``$gt``-style operators
  (database_api_image/utils.py:17-23);
- ``update_one`` on ``_id`` (metadata finished-flips);
- ``aggregate_counts`` — the ``$group``/``$sum`` value-count pipeline used by
  the histogram service (histogram_image/histogram.py:31-36), vectorized
  host-side;
- ``drop`` / ``list_collections``.

Durability model: one JSONL write-ahead log per collection (`<name>.wal`);
each line is an op record (insert/update/delete).  Full state is replayed on
open; ``compact()`` rewrites the log to current state.  All mutation goes
through a per-collection lock; ID allocation is a counter under that lock.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path
from typing import Any, Iterable

from learningorchestra_tpu import faults
from learningorchestra_tpu.concurrency_rt import make_lock, make_rlock

# Collection names become file names; keep them safe.
_NAME_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.\-]*$")


class CollectionExists(Exception):
    pass


class DuplicateKey(Exception):
    """insert_unique target _id already present."""


class CorruptWal(Exception):
    """WAL damaged beyond the torn-tail case a crash can produce."""


class NoSuchCollection(Exception):
    pass


def _match(doc: dict, query: dict | None) -> bool:
    """Mongo-style document match supporting equality and the small operator
    set the reference's GET query path needs ($gt/$gte/$lt/$lte/$ne/$in)."""
    if not query:
        return True
    for key, cond in query.items():
        val = doc.get(key)
        if isinstance(cond, dict):
            for op, operand in cond.items():
                try:
                    if op == "$gt" and not (val is not None and val > operand):
                        return False
                    elif op == "$gte" and not (
                        val is not None and val >= operand
                    ):
                        return False
                    elif op == "$lt" and not (val is not None and val < operand):
                        return False
                    elif op == "$lte" and not (
                        val is not None and val <= operand
                    ):
                        return False
                    elif op == "$ne" and not (val != operand):
                        return False
                    elif op == "$in" and val not in operand:
                        return False
                except TypeError:
                    return False
        else:
            if val != cond:
                return False
    return True


class _Collection:
    def __init__(self, path: Path, durable: bool):
        self.path = path
        self._path_str = str(path)
        self.durable = durable
        self.lock = make_rlock("_Collection.lock")
        self.docs: dict[int, dict] = {}
        self.next_id = 0
        self._fh = None
        self._replayed_off = 0
        if path.exists():
            self._replay()
        self._open_log()

    def _apply(self, op: dict) -> None:
        # next_id must stay monotonic across deletes, so it tracks the max
        # _id ever inserted, not the max surviving doc.
        kind = op["op"]
        if kind == "i":
            doc = op["d"]
            self.docs[doc["_id"]] = doc
            self.next_id = max(self.next_id, doc["_id"] + 1)
        elif kind == "u":
            _id = op["id"]
            if _id in self.docs:
                self.docs[_id].update(op["d"])
        elif kind == "d":
            self.docs.pop(op["id"], None)
        elif kind == "n":
            self.next_id = max(self.next_id, op["v"])

    def _replay(self) -> None:
        data = self.path.read_bytes()
        off = 0
        good_end = 0  # byte offset after the last complete valid record
        torn_at = None
        for raw in data.splitlines(keepends=True):
            end = off + len(raw)
            stripped = raw.strip()
            if not stripped:
                if raw.endswith(b"\n"):
                    good_end = end
                off = end
                continue
            op = None
            if raw.endswith(b"\n"):
                try:
                    op = json.loads(stripped)
                except ValueError:
                    op = None
            if not isinstance(op, dict) or "op" not in op:
                # A crash mid-append leaves exactly one torn record at
                # the TAIL (partial line, or a line cut before its
                # newline).  Stop here; corruption is only tolerable if
                # nothing valid follows (checked below).
                torn_at = off
                break
            self._apply(op)
            good_end = end
            off = end
        if torn_at is not None:
            for raw in data[torn_at:].splitlines(keepends=True)[1:]:
                if not raw.endswith(b"\n"):
                    continue
                try:
                    tail_op = json.loads(raw.strip())
                except ValueError:
                    continue
                if isinstance(tail_op, dict) and "op" in tail_op:
                    # Valid records BEYOND the bad region: that is not
                    # a torn tail, it is mid-file damage — refuse to
                    # silently drop acknowledged writes.
                    raise CorruptWal(
                        f"{self.path}: invalid record at byte "
                        f"{torn_at} followed by valid records — WAL "
                        "is damaged mid-file, refusing to open"
                    )
            # Torn tail only: recover by truncating to the last good
            # record, so the next append starts a CLEAN line instead
            # of gluing itself to partial bytes (which would corrupt
            # the new record too).
            with open(self.path, "r+b") as fh:
                fh.truncate(good_end)
        self._replayed_off = good_end

    def catch_up(self) -> None:
        """Fold in records appended to the WAL since our last replay —
        the cheap half of cross-process coherence (store.refresh).
        Only the UNSEEN tail is read, so a no-change call costs one
        stat.  Our own appends since the last catch-up re-apply
        idempotently (file order IS the serialized history; last write
        per field wins either way).  A torn tail (a peer crashed
        mid-append) stops the scan without truncating — the surviving
        peer's next append runs through ITS recovery, not ours."""
        # Lock-free early exit: callers serialize cross-process under
        # the cluster file lock, and a concurrent IN-process append is
        # already in our doc map (re-applying it later is idempotent),
        # so a stale size check can never lose a peer's record.
        try:
            size = os.stat(self._path_str).st_size
        except FileNotFoundError:
            return
        if size <= self._replayed_off:
            return
        with self.lock:
            with open(self.path, "rb") as fh:
                fh.seek(self._replayed_off)
                data = fh.read()
            off = self._replayed_off
            good_end = off
            for raw in data.splitlines(keepends=True):
                end = off + len(raw)
                stripped = raw.strip()
                if not stripped:
                    if raw.endswith(b"\n"):
                        good_end = end
                    off = end
                    continue
                op = None
                if raw.endswith(b"\n"):
                    try:
                        op = json.loads(stripped)
                    except ValueError:
                        op = None
                if not isinstance(op, dict) or "op" not in op:
                    break  # torn tail: re-scan from here next time
                self._apply(op)
                good_end = end
                off = end
            self._replayed_off = good_end

    def _open_log(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")

    def _append(self, op: dict) -> None:
        # Chaos probe BEFORE the write: an injected failure models a
        # full/failing disk at the WAL boundary — the in-memory doc
        # map may run ahead of the log (exactly what a real fsync
        # failure produces), and recovery is replay-on-reopen.
        faults.hit("store.wal_write")
        self._fh.write(json.dumps(op, default=str) + "\n")
        self._fh.flush()
        if self.durable:
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self.lock:
            if self._fh:
                self._fh.close()
                self._fh = None


class DocumentStore:
    """A directory of collections, each a WAL-backed dict of documents."""

    def __init__(self, root: str | Path, durable_writes: bool = False):
        self.root = Path(root).expanduser()
        self.root.mkdir(parents=True, exist_ok=True)
        self.durable = durable_writes
        self._collections: dict[str, _Collection] = {}
        self._lock = make_lock("DocumentStore._lock")
        for wal in sorted(self.root.glob("*.wal")):
            name = wal.stem
            self._collections[name] = _Collection(wal, durable_writes)

    # -- collection lifecycle -------------------------------------------------

    def _validate_name(self, name: str) -> None:
        if not _NAME_RE.match(name or ""):
            raise ValueError(f"invalid collection name: {name!r}")

    def collection_exists(self, name: str) -> bool:
        with self._lock:
            if name in self._collections:
                return True
        # A collection refresh() popped is still on disk: it EXISTS,
        # the next _get just replays it (multi-process coherence must
        # not make a collection flicker out of existence).
        return (self.root / f"{name}.wal").exists()

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._collections)

    def _get(self, name: str, create: bool = False) -> _Collection:
        with self._lock:
            coll = self._collections.get(name)
            if coll is None:
                path = self.root / f"{name}.wal"
                if not create and not path.exists():
                    raise NoSuchCollection(name)
                self._validate_name(name)
                # Replays the WAL when the file exists — how a
                # collection a PEER process created becomes readable
                # here without an explicit open.
                coll = _Collection(path, self.durable)
                self._collections[name] = coll
            return coll

    def drop(self, name: str) -> bool:
        with self._lock:
            coll = self._collections.pop(name, None)
        if coll is None:
            return False
        coll.close()
        try:
            coll.path.unlink()
        except FileNotFoundError:
            pass
        return True

    def refresh(self, name: str) -> None:
        """Re-read a collection from its WAL, picking up records other
        PROCESSES appended since we last opened it.

        The store's in-memory map is authoritative within one process;
        when several processes share a store root (the multi-engine
        control plane, jobs/cluster.py), each serializes its mutations
        under a cross-process file lock and calls this on entry so it
        folds the others' appends before reading or writing.  Safe to
        call for a collection this process has never opened (the next
        ``_get`` replays the file) or that does not exist at all.
        """
        with self._lock:
            coll = self._collections.get(name)
        if coll is None:
            # Never opened in this process: the next _get replays the
            # file from disk (peer-created collections included).
            return
        coll.catch_up()

    # -- writes ---------------------------------------------------------------

    def insert_one(self, name: str, doc: dict, _id: int | None = None) -> int:
        """Insert, atomically allocating ``_id`` unless one is given."""
        coll = self._get(name, create=True)
        with coll.lock:
            if _id is None:
                _id = coll.next_id
            doc = dict(doc)
            doc["_id"] = _id
            coll.next_id = max(coll.next_id, _id + 1)
            coll.docs[_id] = doc
            coll._append({"op": "i", "d": doc})
            return _id

    def insert_unique(self, name: str, doc: dict, _id: int) -> int:
        """Insert at an explicit ``_id``, failing atomically if present —
        the duplicate-name gate must be check-and-insert under one lock,
        not check-then-insert (two concurrent POSTs with the same name
        must not both succeed)."""
        coll = self._get(name, create=True)
        with coll.lock:
            if _id in coll.docs:
                raise DuplicateKey(f"{name}[{_id}]")
            doc = dict(doc)
            doc["_id"] = _id
            coll.next_id = max(coll.next_id, _id + 1)
            coll.docs[_id] = doc
            coll._append({"op": "i", "d": doc})
            return _id

    def insert_many(self, name: str, docs: Iterable[dict]) -> int:
        """Batched insert (the reference ingests CSV with per-row
        ``insert_one`` — its known bottleneck, database_api_image/
        database.py:139-151; batching is the fix)."""
        coll = self._get(name, create=True)
        n = 0
        with coll.lock:
            lines = []
            for doc in docs:
                doc = dict(doc)
                doc["_id"] = coll.next_id
                coll.next_id += 1
                coll.docs[doc["_id"]] = doc
                lines.append(json.dumps({"op": "i", "d": doc}, default=str))
                n += 1
            if lines:
                faults.hit("store.wal_write")  # batched-append boundary
                coll._fh.write("\n".join(lines) + "\n")
                coll._fh.flush()
                if coll.durable:
                    os.fsync(coll._fh.fileno())
        return n

    def update_one(self, name: str, _id: int, fields: dict) -> bool:
        coll = self._get(name)
        with coll.lock:
            doc = coll.docs.get(_id)
            if doc is None:
                return False
            fields = dict(fields)
            fields.pop("_id", None)
            doc.update(fields)
            coll._append({"op": "u", "id": _id, "d": fields})
            return True

    def compare_and_update(self, name: str, _id: int, expect: dict,
                           fields: dict) -> bool:
        """Atomic compare-and-swap on one document: apply ``fields``
        only if every ``expect`` item currently matches, under the
        collection lock.  The claim table's takeover primitive
        (jobs/cluster.py): two engines racing an expired claim both
        read the same stale owner, but only one CAS lands."""
        try:
            coll = self._get(name)
        except NoSuchCollection:
            return False
        with coll.lock:
            doc = coll.docs.get(_id)
            if doc is None:
                return False
            for key, val in expect.items():
                if doc.get(key) != val:
                    return False
            fields = dict(fields)
            fields.pop("_id", None)
            doc.update(fields)
            coll._append({"op": "u", "id": _id, "d": fields})
            return True

    def delete_one(self, name: str, _id: int) -> bool:
        coll = self._get(name)
        with coll.lock:
            if _id not in coll.docs:
                return False
            del coll.docs[_id]
            coll._append({"op": "d", "id": _id})
            return True

    # -- reads ----------------------------------------------------------------

    def find(
        self,
        name: str,
        query: dict | None = None,
        sort_key: str = "_id",
        skip: int = 0,
        limit: int | None = None,
    ) -> list[dict]:
        """Query → sorted (by ``sort_key``) → skip → limit, mirroring the
        universal GET/poll path (database_api_image/database.py:19-28)."""
        coll = self._get(name)
        with coll.lock:
            docs = [dict(d) for d in coll.docs.values() if _match(d, query)]
        docs.sort(key=lambda d: (d.get(sort_key) is None, d.get(sort_key)))
        if skip:
            docs = docs[skip:]
        if limit is not None:
            docs = docs[:limit]
        return docs

    def find_one(self, name: str, _id: int) -> dict | None:
        try:
            coll = self._get(name)
        except NoSuchCollection:
            return None
        with coll.lock:
            doc = coll.docs.get(_id)
            return dict(doc) if doc is not None else None

    def count(self, name: str, query: dict | None = None) -> int:
        coll = self._get(name)
        with coll.lock:
            if query is None:
                return len(coll.docs)
            return sum(1 for d in coll.docs.values() if _match(d, query))

    def aggregate_counts(
        self, name: str, field: str, exclude_ids: tuple = (0,)
    ) -> dict[Any, int]:
        """Value-count aggregation for histograms — the `$group`/`$sum`
        pipeline of histogram_image/histogram.py:31-36, done host-side."""
        coll = self._get(name)
        counts: dict[Any, int] = {}
        with coll.lock:
            for _id, doc in coll.docs.items():
                if _id in exclude_ids or doc.get("docType") == "execution":
                    continue
                val = doc.get(field)
                if isinstance(val, (list, dict)):
                    val = json.dumps(val, default=str)
                counts[val] = counts.get(val, 0) + 1
        return counts

    # -- maintenance ----------------------------------------------------------

    def compact(self, name: str) -> None:
        """Rewrite a collection's WAL to current state.

        Durability matches the append path: the rewritten file is
        fsync'd BEFORE it replaces the live log (and the directory
        entry after), so a crash mid-compaction can never surface an
        empty/partial collection where a durable one stood.
        """
        coll = self._get(name)
        with coll.lock:
            tmp = coll.path.with_suffix(".wal.tmp")
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(json.dumps({"op": "n", "v": coll.next_id}) + "\n")
                for doc in coll.docs.values():
                    fh.write(json.dumps({"op": "i", "d": doc}, default=str) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            coll._fh.close()
            os.replace(tmp, coll.path)
            dir_fd = os.open(coll.path.parent, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
            coll._open_log()

    def close(self) -> None:
        with self._lock:
            for coll in self._collections.values():
                coll.close()
            self._collections.clear()
