"""Artifact metadata, lineage, and the durable execution ledger.

The cross-cutting data model of the reference (SURVEY §1): every pipeline
artifact is a named collection whose document ``_id=0`` is the metadata
record ``{name, type, finished, timeCreated, parentName?, modulePath?,
class?, method?, fields?, url?}`` (reference:
microservices/database_api_image/utils.py:50-63,
binary_executor_image/utils.py:70-101); the ``finished`` boolean is the
async-completion signal clients poll; ``parentName`` chains give lineage and
the model-lookup walk (binary_executor_image/utils.py:261-280).

Improvements over the reference, deliberate:
- a ``jobState`` field (pending/running/finished/failed) alongside
  ``finished`` — the reference can only express "not finished", which
  conflates running and dead (SURVEY §5.3);
- atomic execution-document ID allocation (the reference's read-then-insert
  races, binary_executor_image/utils.py:116-139);
- lineage-walk loop detection.
"""

from __future__ import annotations

import datetime
from typing import Any

from learningorchestra_tpu.store.document_store import DocumentStore

METADATA_ID = 0


class LineageError(Exception):
    pass


class DuplicateArtifact(Exception):
    """An artifact with this name already exists (API layer maps to 409,
    the reference's duplicate-name conflict —
    database_api_image/server.py:114-136)."""


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ"
    )


class Metadata:
    """Create/read/update the ``_id=0`` metadata document of an artifact."""

    def __init__(self, store: DocumentStore):
        self.store = store

    def create(
        self,
        name: str,
        artifact_type: str,
        *,
        parent_name: str | None = None,
        module_path: str | None = None,
        class_name: str | None = None,
        method: str | None = None,
        extra: dict | None = None,
        overwrite: bool = False,
    ) -> dict:
        doc = {
            "name": name,
            "type": artifact_type,
            "finished": False,
            "jobState": "pending",
            "timeCreated": _now(),
        }
        if parent_name is not None:
            doc["parentName"] = parent_name
        if module_path is not None:
            doc["modulePath"] = module_path
        if class_name is not None:
            doc["class"] = class_name
        if method is not None:
            doc["method"] = method
        if extra:
            doc.update(extra)
        if overwrite:
            self.store.insert_one(name, doc, _id=METADATA_ID)
        else:
            # Atomic check-and-insert: concurrent creates with the same
            # name race to one winner, the loser gets DuplicateArtifact.
            from learningorchestra_tpu.store.document_store import (
                DuplicateKey,
            )

            try:
                self.store.insert_unique(name, doc, _id=METADATA_ID)
            except DuplicateKey as exc:
                raise DuplicateArtifact(name) from exc
        return doc

    def read(self, name: str) -> dict | None:
        return self.store.find_one(name, METADATA_ID)

    def exists(self, name: str) -> bool:
        return self.read(name) is not None

    def is_finished(self, name: str) -> bool:
        doc = self.read(name)
        return bool(doc and doc.get("finished"))

    def get_type(self, name: str) -> str | None:
        doc = self.read(name)
        return doc.get("type") if doc else None

    def update(self, name: str, fields: dict) -> bool:
        return self.store.update_one(name, METADATA_ID, fields)

    def mark_running(self, name: str) -> None:
        self.update(name, {"jobState": "running", "finished": False})

    def mark_finished(self, name: str, extra: dict | None = None) -> None:
        fields = {"jobState": "finished", "finished": True}
        if extra:
            fields.update(extra)
        self.update(name, fields)

    def mark_failed(self, name: str, exception: str) -> None:
        self.update(
            name,
            {"jobState": "failed", "finished": False, "exception": exception},
        )

    def restart(self, name: str) -> None:
        """PATCH re-run semantics: flip back to unfinished/pending
        (reference: binary_executor_image/server.py:110-156)."""
        self.update(
            name,
            {"jobState": "pending", "finished": False, "exception": None},
        )

    # -- lineage --------------------------------------------------------------

    def parent_chain(self, name: str) -> list[dict]:
        """Walk ``parentName`` links upward, loop-safe; returns metadata docs
        from ``name`` to the root."""
        chain: list[dict] = []
        seen: set[str] = set()
        cur: str | None = name
        while cur is not None:
            if cur in seen:
                raise LineageError(f"lineage cycle at {cur!r}")
            seen.add(cur)
            doc = self.read(cur)
            if doc is None:
                raise LineageError(f"missing artifact in lineage: {cur!r}")
            chain.append(doc)
            cur = doc.get("parentName")
        return chain

    def find_model_ancestor(self, name: str) -> dict:
        """Walk the parent chain upward until an artifact of type ``model/*``
        — how a predict step finds the original model spec behind a train
        step (reference: binary_executor_image/utils.py:261-280)."""
        for doc in self.parent_chain(name):
            if str(doc.get("type", "")).startswith("model"):
                return doc
        raise LineageError(f"no model ancestor for {name!r}")


class ExecutionLedger:
    """Append-only per-artifact execution records at ``_id>=1``.

    Every job appends a document recording what ran and how it ended —
    the reference's durable observability (binary_executor_image/
    binary_execution.py:174-186, code_executor_image/utils.py:113-138,
    which additionally captures stdout as ``functionMessage``).
    """

    def __init__(self, store: DocumentStore):
        self.store = store

    def record(
        self,
        name: str,
        *,
        description: str | None = None,
        method: str | None = None,
        parameters: Any = None,
        state: str = "finished",
        exception: str | None = None,
        stdout: str | None = None,
        metrics: dict | None = None,
        trace: dict | None = None,
    ) -> int:
        doc: dict = {
            "executionTime": _now(),
            "state": state,
            # Execution records share the artifact's collection (the
            # reference's contract — clients see them in GET results), but
            # are tagged so data reads (DataFrames, histograms, projections)
            # can exclude them.
            "docType": "execution",
        }
        if description is not None:
            doc["description"] = description
        if method is not None:
            doc["method"] = method
        if parameters is not None:
            doc["parameters"] = parameters
        if exception is not None:
            doc["exception"] = exception
        if stdout is not None:
            doc["functionMessage"] = stdout
        if metrics:
            doc["metrics"] = metrics
        if trace:
            # The job's span record (obs/tracing.py): queue wait,
            # lease, compile, per-epoch steps — served back by
            # GET /observability/jobs/<name>/trace.
            doc["trace"] = trace
        return self.store.insert_one(name, doc)

    def history(self, name: str) -> list[dict]:
        return self.store.find(name, query={"docType": "execution"})


class ArtifactStore:
    """Facade tying the document store, metadata and ledger together.

    One per process; services receive this rather than raw stores.
    """

    def __init__(self, store: DocumentStore):
        self.documents = store
        self.metadata = Metadata(store)
        self.ledger = ExecutionLedger(store)

    # Universal GET/poll read path: metadata doc first, then rows
    # (reference: database_api_image/server.py:52-80 — metadata appears
    # first because results sort on _id and metadata is _id=0).
    def read_page(
        self,
        name: str,
        query: dict | None = None,
        skip: int = 0,
        limit: int = 20,
    ) -> list[dict]:
        return self.documents.find(
            name, query=query, sort_key="_id", skip=skip, limit=limit
        )

    def list_by_type(self, artifact_type_prefix: str = "") -> list[dict]:
        """Metadata of all artifacts whose type starts with a prefix
        (reference: database_api_image/server.py:83-93 lists by type)."""
        out = []
        for coll in self.documents.list_collections():
            meta = self.metadata.read(coll)
            if meta and str(meta.get("type", "")).startswith(
                artifact_type_prefix
            ):
                out.append(meta)
        return out

    def delete(self, name: str) -> bool:
        return self.documents.drop(name)
