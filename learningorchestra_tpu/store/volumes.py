"""Volume-backed binary artifact storage.

The reference persists model instances and transform outputs as files on
service-type-keyed Docker volumes — keras SavedModel when possible, dill
otherwise (reference: microservices/binary_executor_image/utils.py:199-251,
model_image/utils.py:186-210).  Here the same contract is a host directory
tree keyed by service type, with three formats:

- ``pytree``: JAX pytrees (model params / optimizer states) saved as an
  orbax-style checkpoint directory — the TPU-native replacement for keras
  SavedModel, shard-friendly and HBM↔host explicit;
- ``dill``: arbitrary Python objects (classical estimators, tuples of
  arrays) — the reference's fallback path, kept for parity;
- ``bytes``: raw streams (generic dataset ingest,
  database_api_image/database.py:61-83).
"""

from __future__ import annotations

import io
import os
import re
import shutil
from pathlib import Path
from typing import Any

import dill

# Same grammar as DocumentStore collection names: binary names come from
# REST request JSON and become file names — no separators, no traversal.
_NAME_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.\-]*$")


def _validate_name(name: str) -> str:
    if not _NAME_RE.match(name or "") or ".." in name:
        raise ValueError(f"invalid artifact name: {name!r}")
    return name

# Service-type → volume directory, mirroring the reference's six named
# volumes (binary_executor_image/Dockerfile:10-13, docker-compose.yml:355-363).
VOLUME_KEYS = (
    "datasets",
    "models",
    "binaries",
    "transform",
    "explore",
    "code_executions",
)


def volume_key_for_type(artifact_type: str) -> str:
    """Map an artifact type like ``train/tensorflow`` to its volume."""
    head = artifact_type.split("/", 1)[0]
    return {
        "dataset": "datasets",
        "model": "models",
        "train": "binaries",
        "tune": "binaries",
        "evaluate": "binaries",
        "predict": "binaries",
        "builder": "binaries",
        "transform": "transform",
        "explore": "explore",
        "function": "code_executions",
    }.get(head, "binaries")


class VolumeStorage:
    def __init__(self, root: str | Path):
        self.root = Path(root).expanduser()
        for key in VOLUME_KEYS:
            (self.root / key).mkdir(parents=True, exist_ok=True)

    def path_for(self, artifact_type: str, name: str) -> Path:
        return self.root / volume_key_for_type(artifact_type) / _validate_name(
            name
        )

    # -- dill (parity fallback) ----------------------------------------------

    def save_object(self, artifact_type: str, name: str, obj: Any) -> Path:
        return self._dump_atomic(self.path_for(artifact_type, name), obj)

    @staticmethod
    def _dump_atomic(path: Path, obj: Any) -> Path:
        """tmp + rename publish: a PATCH re-run rewriting a binary
        while a concurrent job dill-loads it must never expose a torn
        file (same discipline as the shard writer's os.replace)."""
        path.parent.mkdir(parents=True, exist_ok=True)
        # Leading '.' can never collide with an artifact binary:
        # _NAME_RE requires names to start with an alphanumeric.
        tmp = path.with_name("." + path.name + ".tmp")
        with open(tmp, "wb") as fh:
            dill.dump(obj, fh)
        os.replace(tmp, path)
        return path

    def read_object(self, artifact_type: str, name: str) -> Any:
        path = self.path_for(artifact_type, name)
        with open(path, "rb") as fh:
            return dill.load(fh)

    # -- pytree checkpoints (TPU-native model persistence) --------------------

    def save_pytree(self, artifact_type: str, name: str, tree: Any) -> Path:
        """Checkpoint a JAX pytree.  Arrays are device_get'd to host before
        serialization so the HBM↔host boundary is explicit at the job edge
        (SURVEY §5.4 TPU-native plan)."""
        import jax
        import numpy as np

        host_tree = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x))
            if hasattr(x, "shape")
            else x,
            tree,
        )
        return self._dump_atomic(self.path_for(artifact_type, name),
                                 host_tree)

    def read_pytree(self, artifact_type: str, name: str) -> Any:
        return self.read_object(artifact_type, name)

    # -- raw bytes ------------------------------------------------------------

    def save_stream(
        self, artifact_type: str, name: str, stream: io.BufferedIOBase,
        chunk_size: int = 1 << 20,
    ) -> Path:
        path = self.path_for(artifact_type, name)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "wb") as fh:
            shutil.copyfileobj(stream, fh, chunk_size)
        return path

    def read_bytes(self, artifact_type: str, name: str) -> bytes:
        return self.path_for(artifact_type, name).read_bytes()

    # -- lifecycle ------------------------------------------------------------

    def exists(self, artifact_type: str, name: str) -> bool:
        return self.path_for(artifact_type, name).exists()

    def delete(self, artifact_type: str, name: str) -> bool:
        path = self.path_for(artifact_type, name)
        if path.is_dir():
            shutil.rmtree(path)
            return True
        if path.exists():
            path.unlink()
            return True
        return False

    def delete_everywhere(self, name: str) -> bool:
        """Remove a named binary from whichever volume holds it."""
        _validate_name(name)
        hit = False
        for key in VOLUME_KEYS:
            path = self.root / key / name
            if path.is_dir():
                shutil.rmtree(path)
                hit = True
            elif path.exists():
                path.unlink()
                hit = True
        return hit
