"""Artifact persistence: embedded document store, metadata/lineage, volumes.

Replaces the reference's MongoDB replica set + named Docker volumes
(reference: docker-compose.yml:42-100, 355-363) with an embedded,
write-ahead-logged document store and a host-filesystem object store —
while keeping the exact artifact contract every reference service relies on:
a named collection whose document ``_id=0`` is the metadata record
(``finished`` flag, lineage via ``parentName``), result rows at ``_id>=1``
(reference: microservices/database_api_image/utils.py:50-63,
binary_executor_image/utils.py:70-139).
"""

from learningorchestra_tpu.store.document_store import DocumentStore
from learningorchestra_tpu.store.artifacts import (
    ArtifactStore,
    Metadata,
    LineageError,
    DuplicateArtifact,
)
from learningorchestra_tpu.store.volumes import VolumeStorage

__all__ = [
    "DocumentStore",
    "ArtifactStore",
    "Metadata",
    "LineageError",
    "DuplicateArtifact",
    "VolumeStorage",
]
