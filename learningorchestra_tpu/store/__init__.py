"""Artifact persistence: embedded document store, metadata/lineage, volumes.

Replaces the reference's MongoDB replica set + named Docker volumes
(reference: docker-compose.yml:42-100, 355-363) with an embedded,
write-ahead-logged document store and a host-filesystem object store —
while keeping the exact artifact contract every reference service relies on:
a named collection whose document ``_id=0`` is the metadata record
(``finished`` flag, lineage via ``parentName``), result rows at ``_id>=1``
(reference: microservices/database_api_image/utils.py:50-63,
binary_executor_image/utils.py:70-139).
"""

from learningorchestra_tpu.store.document_store import DocumentStore
from learningorchestra_tpu.store.artifacts import (
    ArtifactStore,
    Metadata,
    LineageError,
    DuplicateArtifact,
)
from learningorchestra_tpu.store.volumes import VolumeStorage


def open_document_store(root, durable_writes: bool = False,
                        backend: str = "auto"):
    """Open the system-of-record at ``root``.

    ``backend``: ``"native"`` (C++ liblodstore), ``"python"`` (embedded
    WAL store), or ``"auto"`` — native when the library builds, Python
    otherwise.  Both backends share one WAL format, so a directory
    written by either opens under the other.
    """
    if backend not in ("auto", "native", "python"):
        raise ValueError(f"unknown store backend: {backend!r}")
    if backend in ("auto", "native"):
        try:
            from learningorchestra_tpu.native import NativeDocumentStore

            return NativeDocumentStore(root, durable_writes=durable_writes)
        except Exception:
            if backend == "native":
                raise
    return DocumentStore(root, durable_writes=durable_writes)


__all__ = [
    "DocumentStore",
    "open_document_store",
    "ArtifactStore",
    "Metadata",
    "LineageError",
    "DuplicateArtifact",
    "VolumeStorage",
]
