"""Sharded (beyond-host-RAM) dataset artifacts.

The reference streams arbitrarily large datasets row-by-row into MongoDB
and trains by reading rows back per worker (reference:
microservices/database_api_image/database.py:86-151 — a 3-thread
download→treat→save queue; training reads the collection back).  A
row-document store is the wrong layout for a TPU input pipeline: training
wants large contiguous numeric blocks it can ``device_put`` whole, not
per-row BSON.  Here ingest writes fixed-size COLUMNAR SHARDS (one ``.npz``
per shard, one array per column) plus a JSON manifest; the training paths
stream shard k+1 from disk while the device runs shard k, so peak host
memory is O(shard), not O(dataset) — BASELINE config 5's
ResNet-on-ImageNet shape, which can never materialize as one host array.

Layout::

    <root>/manifest.json                 fields, dtypes, shard row counts
    <root>/shard_00000.npz               {field: ndarray(rows_k,)}
    ...

Shuffle model (the standard sharded-pipeline trade): shard ORDER is
reshuffled every epoch on the host, row order WITHIN a shard on the
device; sample-granular global shuffling would re-read the whole dataset
per epoch.  Rows land in shards in ingest order, so pre-shuffled sources
keep their mixing; pathologically ordered sources should raise
``rows_per_shard`` or pre-shuffle.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

MANIFEST = "manifest.json"
_SHARD_FMT = "shard_{:05d}.npz"

# int64 CSV values narrow to int32 (TPU-native int width; jax defaults to
# 32-bit anyway) and float64 to float32.
_NARROW = {"int64": "int32", "float64": "float32"}


def _narrow(dtype: np.dtype) -> str:
    name = np.dtype(dtype).name
    return _NARROW.get(name, name)


def _int32_safe(arr: np.ndarray) -> bool:
    """True when every value fits int32 exactly (INT32_MIN included).
    ONE policy for both ingest paths — the cross-engine dtype parity
    (ADVICE r3) depends on these bounds never drifting apart."""
    return bool(
        arr.size == 0
        or (np.all(arr >= -(2**31)) and np.all(arr < 2**31))
    )


class ShardedDatasetWriter:
    """Streaming writer: buffer rows, flush one ``.npz`` per shard.

    Columns may change integer/float character between shards (a column
    integral for the first million rows then fractional); the manifest
    records the PROMOTED dtype and readers cast each shard on load, so
    every shard a consumer sees is uniformly typed.
    """

    def __init__(self, root: str | Path, fields: list[str], *,
                 rows_per_shard: int = 65536):
        if rows_per_shard <= 0:
            raise ValueError("rows_per_shard must be positive")
        if not fields:
            raise ValueError("sharded dataset needs a non-empty header")
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.fields = list(fields)
        self.rows_per_shard = rows_per_shard
        self._buf: list[list] = []
        self._blocks: list[np.ndarray] = []
        self._block_rows = 0
        self._shard_rows: list[int] = []
        self._dtypes: dict[str, np.dtype] = {}
        # Per-field "saw float-FORMATTED text" flags for block mode:
        # the caller's parser (native CSV) reports them so both ingest
        # paths type columns by text format, not value (ADVICE r3).
        self._float_format = np.zeros(len(self.fields), bool)
        self._closed = False

    def append(self, row: list) -> None:
        """One row of numeric values in field order (shorter rows are an
        error — silent column misalignment corrupts training data)."""
        if len(row) != len(self.fields):
            raise ValueError(
                f"row has {len(row)} values, header has "
                f"{len(self.fields)} fields"
            )
        if self._blocks:
            raise RuntimeError("append after append_block: pick one")
        self._buf.append(row)
        if len(self._buf) >= self.rows_per_shard:
            self._flush()

    def append_block(self, block, float_format_cols=None) -> None:
        """Bulk append a ``(n, n_fields)`` float64 array (the native
        CSV parser's output) — no per-row Python objects.  Row and
        block modes don't mix on one writer (ordering would interleave
        wrongly).

        ``float_format_cols`` (len-``n_fields`` bool mask) marks
        columns whose TEXT was float-formatted somewhere in this block
        ("5.0", "1e3"): they stay float32 even when every value is
        integral, matching the row path's ``_infer`` semantics exactly
        — training-loss selection must not depend on which ingest
        engine ran (ADVICE r3).  Without the mask, integral columns
        narrow by value (the pre-r4 behavior)."""
        if self._buf:
            raise RuntimeError("append_block after append: pick one")
        block = np.asarray(block, np.float64)
        if block.ndim != 2 or block.shape[1] != len(self.fields):
            raise ValueError(
                f"block shape {block.shape} != (n, {len(self.fields)})"
            )
        if float_format_cols is not None:
            self._float_format |= np.asarray(float_format_cols, bool)
        self._blocks.append(block)
        self._block_rows += len(block)
        while self._block_rows >= self.rows_per_shard:
            self._flush_block(self.rows_per_shard)

    def _take_block_rows(self, n: int) -> np.ndarray:
        """Pop exactly n rows off the block queue (concat-free when a
        single block covers them)."""
        out, need = [], n
        while need > 0:
            head = self._blocks[0]
            if len(head) <= need:
                out.append(head)
                need -= len(head)
                self._blocks.pop(0)
            else:
                out.append(head[:need])
                self._blocks[0] = head[need:]
                need = 0
        self._block_rows -= n
        return out[0] if len(out) == 1 else np.concatenate(out, axis=0)

    def _flush_block(self, n: int) -> None:
        if n <= 0:
            return
        rows = self._take_block_rows(n)
        cols = {}
        for i, field in enumerate(self.fields):
            arr = rows[:, i]
            # Mirror the row path's dtype inference: int32 only when
            # no cell was float-FORMATTED (mask from the parser) and
            # the values are integral, finite, and int32-safe.
            if (not self._float_format[i]) and np.all(
                np.isfinite(arr)
            ) and np.all(arr == np.floor(arr)) and _int32_safe(arr):
                arr = arr.astype(np.int32)
            else:
                arr = arr.astype(np.float32)
            cols[field] = arr
            prev = self._dtypes.get(field)
            self._dtypes[field] = arr.dtype if prev is None else np.dtype(
                _narrow(np.promote_types(prev, arr.dtype))
            )
        self._publish_shard(cols, n)

    def _flush(self) -> None:
        if not self._buf:
            return
        cols = {}
        for i, field in enumerate(self.fields):
            try:
                arr = np.asarray([r[i] for r in self._buf])
            except (ValueError, TypeError) as exc:
                raise ValueError(
                    f"column {field!r} is not numeric: {exc}"
                ) from exc
            if not np.issubdtype(arr.dtype, np.number):
                raise ValueError(
                    f"column {field!r} is not numeric "
                    f"(dtype {arr.dtype}); cast or project it away "
                    "before sharded ingest"
                )
            if np.issubdtype(arr.dtype, np.integer) and not _int32_safe(
                arr
            ):
                # int64 values beyond int32 must not wrap silently on
                # the narrowing cast; degrade to float32 like the
                # block path's int32-safety check.
                arr = arr.astype(np.float32)
            else:
                arr = arr.astype(_narrow(arr.dtype))
            cols[field] = arr
            prev = self._dtypes.get(field)
            if prev is None:
                self._dtypes[field] = arr.dtype
            else:
                # Re-narrow after promotion: int32+float32 promotes to
                # float64 under numpy's rules, but shards stay 32-bit.
                self._dtypes[field] = np.dtype(
                    _narrow(np.promote_types(prev, arr.dtype))
                )
        n = len(self._buf)
        self._buf = []
        self._publish_shard(cols, n)

    def _publish_shard(self, cols: dict, n: int) -> None:
        k = len(self._shard_rows)
        # Atomic publish: a crashed ingest must not leave a torn .npz a
        # later open() would try to read.
        tmp = self.root / (_SHARD_FMT.format(k) + ".tmp")
        with open(tmp, "wb") as fh:
            np.savez(fh, **cols)
        os.replace(tmp, self.root / _SHARD_FMT.format(k))
        self._shard_rows.append(n)

    def close(self) -> dict:
        """Flush the tail shard and publish the manifest (the artifact
        does not exist as a dataset until the manifest lands)."""
        if self._closed:
            raise RuntimeError("writer already closed")
        self._flush()
        self._flush_block(self._block_rows)
        self._closed = True
        manifest = {
            "fields": self.fields,
            "dtypes": {
                f: np.dtype(self._dtypes.get(f, np.float32)).name
                for f in self.fields
            },
            "shard_rows": self._shard_rows,
            "rows": int(sum(self._shard_rows)),
            "rows_per_shard": self.rows_per_shard,
        }
        tmp = self.root / (MANIFEST + ".tmp")
        tmp.write_text(json.dumps(manifest))
        os.replace(tmp, self.root / MANIFEST)
        return manifest


class ShardedTensorWriter:
    """Streaming writer for N-D (tensor) columns — the image-dataset
    shape (BASELINE config 5: ResNet/ImageNet), where a row's features
    are a (H, W, C) block, not scalars.  Chunks of rows arrive as
    arrays ({column: (k, *feature_shape)}) and flush into the same
    shard/manifest layout the scalar writer produces, so every reader
    (views, streaming fit, replica of the volume) works unchanged.
    """

    def __init__(self, root: str | Path, column_shapes: dict, *,
                 rows_per_shard: int = 4096):
        if rows_per_shard <= 0:
            raise ValueError("rows_per_shard must be positive")
        if not column_shapes:
            raise ValueError("tensor dataset needs columns")
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.fields = list(column_shapes)
        self.column_shapes = {
            f: tuple(s) for f, s in column_shapes.items()
        }
        self.rows_per_shard = rows_per_shard
        self._buf: dict[str, list] = {f: [] for f in self.fields}
        self._buffered = 0
        self._shard_rows: list[int] = []
        self._dtypes: dict[str, np.dtype] = {}
        self._closed = False

    def append_rows(self, chunk: dict) -> None:
        """A chunk of rows per column: {field: (k, *field_shape)}.
        All columns must bring the same k."""
        sizes = set()
        for field in self.fields:
            arr = np.asarray(chunk[field])
            want = self.column_shapes[field]
            if tuple(arr.shape[1:]) != want:
                raise ValueError(
                    f"column {field!r} rows have shape "
                    f"{arr.shape[1:]}, dataset declares {want}"
                )
            if not np.issubdtype(arr.dtype, np.number):
                raise ValueError(f"column {field!r} is not numeric")
            sizes.add(arr.shape[0])
        if len(sizes) != 1:
            raise ValueError(f"columns brought differing row counts: "
                             f"{sorted(sizes)}")
        k = sizes.pop()
        # Convert ONCE per chunk (astype only copies on a real dtype
        # change), not per shard-boundary crossing.
        converted = {}
        for field in self.fields:
            arr = np.asarray(chunk[field])
            want = np.dtype(_narrow(arr.dtype))
            converted[field] = arr.astype(want, copy=False)
        off = 0
        while off < k:
            room = self.rows_per_shard - self._buffered
            take = min(room, k - off)
            for field in self.fields:
                self._buf[field].append(
                    converted[field][off:off + take]
                )
            self._buffered += take
            off += take
            if self._buffered >= self.rows_per_shard:
                self._flush()

    def _flush(self) -> None:
        if not self._buffered:
            return
        cols = {}
        for field in self.fields:
            arr = np.concatenate(self._buf[field], axis=0)
            cols[field] = arr
            prev = self._dtypes.get(field)
            self._dtypes[field] = arr.dtype if prev is None else \
                np.dtype(_narrow(np.promote_types(prev, arr.dtype)))
            self._buf[field] = []
        k = len(self._shard_rows)
        tmp = self.root / (_SHARD_FMT.format(k) + ".tmp")
        with open(tmp, "wb") as fh:
            np.savez(fh, **cols)
        os.replace(tmp, self.root / _SHARD_FMT.format(k))
        self._shard_rows.append(self._buffered)
        self._buffered = 0

    def close(self) -> dict:
        if self._closed:
            raise RuntimeError("writer already closed")
        self._flush()
        self._closed = True
        manifest = {
            "fields": self.fields,
            "dtypes": {
                f: np.dtype(self._dtypes.get(f, np.float32)).name
                for f in self.fields
            },
            "column_shapes": {
                f: list(s) for f, s in self.column_shapes.items()
            },
            "shard_rows": self._shard_rows,
            "rows": int(sum(self._shard_rows)),
            "rows_per_shard": self.rows_per_shard,
        }
        tmp = self.root / (MANIFEST + ".tmp")
        tmp.write_text(json.dumps(manifest))
        os.replace(tmp, self.root / MANIFEST)
        return manifest


class ShardedDataset:
    """Read handle over a sharded dataset directory — lazy: holds the
    manifest only; shards load one at a time via :meth:`load_shard`."""

    def __init__(self, root: str | Path):
        self.root = Path(root)
        path = self.root / MANIFEST
        if not path.exists():
            raise FileNotFoundError(
                f"no sharded-dataset manifest at {path} (ingest "
                "unfinished or crashed before publish)"
            )
        m = json.loads(path.read_text())
        self.fields: list[str] = list(m["fields"])
        self.dtypes = {f: np.dtype(d) for f, d in m["dtypes"].items()}
        self.shard_rows: list[int] = [int(r) for r in m["shard_rows"]]
        self.n_rows: int = int(m["rows"])
        self.rows_per_shard: int = int(m["rows_per_shard"])
        # Tensor datasets (ShardedTensorWriter) record per-column row
        # shapes; scalar datasets predate the key and default to ().
        self.column_shapes: dict[str, tuple] = {
            f: tuple(s)
            for f, s in (m.get("column_shapes") or {}).items()
        }

    # -- handle surface -------------------------------------------------------

    @property
    def n_shards(self) -> int:
        return len(self.shard_rows)

    def __len__(self) -> int:
        return self.n_rows

    def __getitem__(self, key):
        """``$dataset.column`` DSL indexing → a single-column view;
        a list of names → a feature-matrix view."""
        return self.view(key)

    def view(self, cols) -> "ShardedView":
        return ShardedView(self, cols)

    def feature_view(self, exclude) -> "ShardedView":
        """All columns except ``exclude`` — the ``fit(x=$big,
        y=$big.label)`` convention resolves x to this."""
        drop = {exclude} if isinstance(exclude, str) else set(exclude)
        keep = [f for f in self.fields if f not in drop]
        if not keep:
            raise ValueError("feature view excludes every column")
        return ShardedView(self, keep)

    def load_shard(self, k: int, cols: list[str] | None = None) -> dict:
        """Columns of shard ``k`` as host arrays, cast to the manifest
        dtypes (shards written before a column promoted may be narrower
        on disk)."""
        with np.load(self.root / _SHARD_FMT.format(k)) as z:
            out = {}
            for f in (cols or self.fields):
                arr = z[f]
                want = self.dtypes[f]
                out[f] = arr.astype(want) if arr.dtype != want else arr
            return out


class ShardedView:
    """Lazy column selection over a :class:`ShardedDataset`.

    A string selects ONE column — scalar columns yield (rows,), tensor
    columns (ShardedTensorWriter) yield (rows, *feature_shape).  A
    list selects a feature matrix (rows, n_cols) stacked in the given
    order, promoted to a common dtype; a one-element list over a
    tensor column collapses to that column (``feature_view`` on a
    tensor dataset resolves to its x block).  Mixing tensor columns
    into a multi-column matrix is an error — there is no meaningful
    stacking axis.
    """

    def __init__(self, dataset: ShardedDataset, cols):
        self.dataset = dataset
        single = isinstance(cols, str)
        names = [cols] if single else list(cols)
        missing = [c for c in names if c not in dataset.fields]
        if missing:
            raise KeyError(
                f"no such column(s) {missing} in sharded dataset "
                f"(fields: {dataset.fields})"
            )
        nd = [c for c in names if dataset.column_shapes.get(c)]
        if not single and len(names) == 1 and nd:
            # A one-element list over a TENSOR column collapses to the
            # column itself (feature_view on a tensor dataset).  A
            # one-element list over a scalar column stays a (rows, 1)
            # matrix — the shape the in-memory DataFrame path feeds
            # single-feature models.
            single = True
        elif nd and not single:
            raise ValueError(
                f"tensor column(s) {nd} cannot stack into a feature "
                "matrix; select one column"
            )
        self.single = single
        self.cols = names

    def __len__(self) -> int:
        return self.dataset.n_rows

    @property
    def dtype(self) -> np.dtype:
        dts = [self.dataset.dtypes[c] for c in self.cols]
        out = dts[0]
        for d in dts[1:]:
            out = np.promote_types(out, d)
        return out

    @property
    def shape(self) -> tuple:
        n = self.dataset.n_rows
        if self.single:
            row = self.dataset.column_shapes.get(self.cols[0], ())
            return (n, *row)
        return (n, len(self.cols))

    def load_shard(self, k: int) -> np.ndarray:
        cols = self.dataset.load_shard(k, self.cols)
        if self.single:
            return cols[self.cols[0]]
        dtype = self.dtype
        return np.stack(
            [cols[c].astype(dtype) for c in self.cols], axis=1
        )

    def head(self, n: int = 1) -> np.ndarray:
        """First ``n`` rows (for parameter init / loss resolution)
        without loading more than the first shard."""
        return self.load_shard(0)[:n]


def same_dataset(a, b) -> bool:
    """True when two views stream from the same dataset directory —
    the x/y alignment precondition for streaming fit."""
    da = a.dataset if isinstance(a, ShardedView) else a
    db = b.dataset if isinstance(b, ShardedView) else b
    return isinstance(da, ShardedDataset) and \
        isinstance(db, ShardedDataset) and da.root == db.root


def resolve_xy_views(x, y):
    """Normalize/validate the (x, y) pair every streaming surface
    accepts: y must be one column; a bare-dataset x resolves to all
    columns except y's (the ``fit(x="$big", y="$big.label")`` request
    shape); both must stream from ONE dataset (shard alignment).
    Returns ``(x_view, y_view)``."""
    if isinstance(y, ShardedDataset) or not (
        isinstance(y, ShardedView) and y.single
    ):
        raise ValueError(
            "y must select one column of the sharded dataset "
            "(request shape: \"y\": \"$name.label\")"
        )
    if isinstance(x, ShardedDataset):
        x = x.feature_view(y.cols[0])
    if not isinstance(x, ShardedView):
        raise ValueError(
            "x must be a sharded view when y is one (both sides "
            "stream shard-aligned from the same dataset)"
        )
    if not same_dataset(x, y):
        raise ValueError(
            "x and y stream from different sharded datasets; "
            "shard alignment requires one source"
        )
    return x, y


class WeightedMetrics:
    """Row-weighted metric accumulation across shards.

    Perplexity is averaged in LOG domain (a shard's ppl is exp of its
    mean CE, so mean-of-logs + exp-at-the-end reproduces the global
    exp-after-mean; averaging exps would Jensen-bias upward) — shared
    by every streaming loop so the convention can't drift.
    """

    def __init__(self):
        self._totals: dict[str, float] = {}
        self._weight = 0.0

    def add(self, metrics: dict, rows: float) -> None:
        for key, val in metrics.items():
            val = float(val)
            if key == "perplexity":
                val = float(np.log(val))
            self._totals[key] = self._totals.get(key, 0.0) + val * rows
        self._weight += rows

    def result(self) -> dict:
        out = {k: v / self._weight for k, v in self._totals.items()}
        if "perplexity" in out:
            out["perplexity"] = float(np.exp(out["perplexity"]))
        return out
