"""WAL-shipping read replica for the document store.

The reference deploys a 3-node MongoDB replica set for persistence HA
(reference: docker-compose.yml:42-90 — mongo + two mongo-secondary
replicas behind a replSetInitiate).  The store here is a per-collection
JSONL write-ahead log (document_store.py), which makes replication a
byte-shipping problem instead of a protocol: a follower tails each
``<name>.wal``, appends the complete records to its OWN copy (fsync'd —
the replica must survive its own crash), and applies them to a live
read view.  Failover is :meth:`WalReplica.promote`: the replica
directory IS a valid store directory, so promotion is just opening it
for writes.

Semantics:

- **Record-aligned shipping.**  Only byte ranges ending in a complete
  ``\\n``-terminated record ship; a torn tail on the primary (crash
  mid-append) is never copied, mirroring the primary's own recovery.
- **Compaction/rewrite detection.**  ``compact()`` rewrites a WAL in
  place; the follower detects the file shrinking below its shipped
  offset and resyncs that collection from byte 0 (same for a dropped
  and recreated collection).
- **Pull model.**  ``sync()`` is explicit — call it on a timer, or
  from a cron/sidecar.  The primary needs no cooperation beyond its
  ordinary appends, exactly like shipping WALs off a Postgres primary.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from learningorchestra_tpu.store.document_store import (
    DocumentStore,
    _match,
)


class WalReplica:
    """Read-only follower of a primary store directory."""

    def __init__(self, primary_root: str | Path,
                 replica_root: str | Path):
        self.primary_root = Path(primary_root)
        self.replica_root = Path(replica_root)
        self.replica_root.mkdir(parents=True, exist_ok=True)
        self._offsets: dict[str, int] = {}
        self._docs: dict[str, dict[int, dict]] = {}
        # Bootstrap from whatever the replica dir already holds (a
        # follower restarting must not re-apply from zero into
        # duplicated state — offsets persist next to the shipped WALs).
        for wal in sorted(self.replica_root.glob("*.wal")):
            name = wal.stem
            self._offsets[name] = wal.stat().st_size
            self._docs[name] = {}
            self._apply_bytes(name, wal.read_bytes())

    # -- shipping -------------------------------------------------------------

    def sync(self) -> dict:
        """Ship new complete records for every primary collection;
        returns {collection: bytes_shipped}."""
        shipped: dict[str, int] = {}
        seen = set()
        for wal in sorted(self.primary_root.glob("*.wal")):
            name = wal.stem
            seen.add(name)
            shipped[name] = self._sync_one(name, wal)
        # Collections dropped on the primary disappear here too —
        # otherwise a promote would resurrect deleted data.
        for name in list(self._offsets):
            if name not in seen:
                self._offsets.pop(name, None)
                self._docs.pop(name, None)
                dst = self.replica_root / f"{name}.wal"
                if dst.exists():
                    dst.unlink()
        return shipped

    # Shipped-tail window compared against the primary on every sync:
    # detects a COMPACTED-then-REGROWN WAL whose size passed our offset
    # again (size alone can't) — mid-record shipping would silently
    # diverge the replica.
    TAIL_CHECK = 64

    def _sync_one(self, name: str, src: Path) -> int:
        offset = self._offsets.get(name, 0)
        try:
            size = src.stat().st_size
        except FileNotFoundError:
            return 0
        rewritten = size < offset
        if not rewritten and offset > 0:
            # Same-or-larger size: confirm the primary still holds the
            # bytes we shipped by comparing the tail window.
            dst = self.replica_root / f"{name}.wal"
            check = min(self.TAIL_CHECK, offset)
            with open(src, "rb") as fh:
                fh.seek(offset - check)
                primary_tail = fh.read(check)
            with open(dst, "rb") as fh:
                fh.seek(offset - check)
                replica_tail = fh.read(check)
            rewritten = primary_tail != replica_tail
        if rewritten:
            # Compaction (or drop+recreate) rewrote the file: restart
            # this collection from byte 0.
            offset = 0
            self._docs[name] = {}
            dst = self.replica_root / f"{name}.wal"
            if dst.exists():
                dst.unlink()
        with open(src, "rb") as fh:
            fh.seek(offset)
            data = fh.read()
        # Ship complete records only: hold back everything past the
        # last newline (a mid-append torn tail must not replicate).
        cut = data.rfind(b"\n")
        if cut < 0:
            return 0
        chunk = data[: cut + 1]
        dst = self.replica_root / f"{name}.wal"
        with open(dst, "ab") as fh:
            fh.write(chunk)
            fh.flush()
            os.fsync(fh.fileno())
        self._offsets[name] = offset + len(chunk)
        self._apply_bytes(name, chunk)
        return len(chunk)

    def _apply_bytes(self, name: str, data: bytes) -> None:
        docs = self._docs.setdefault(name, {})
        for raw in data.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                op = json.loads(raw)
            except ValueError:
                continue  # primary torn tail shipped pre-fix; skip
            kind = op.get("op")
            if kind == "i":
                docs[op["d"]["_id"]] = op["d"]
            elif kind == "u":
                if op["id"] in docs:
                    docs[op["id"]].update(op["d"])
            elif kind == "d":
                docs.pop(op["id"], None)

    # -- read surface ---------------------------------------------------------

    def list_collections(self) -> list[str]:
        return sorted(self._docs)

    def count(self, name: str, query: dict | None = None) -> int:
        return len(self.find(name, query))

    def find(self, name: str, query: dict | None = None) -> list[dict]:
        docs = self._docs.get(name, {})
        return [
            dict(d) for _id, d in sorted(docs.items())
            if _match(d, query)
        ]

    def find_one(self, name: str, _id: int) -> dict | None:
        doc = self._docs.get(name, {}).get(_id)
        return dict(doc) if doc is not None else None

    def lag_bytes(self) -> int:
        """Total unshipped primary bytes — the replication-lag gauge."""
        lag = 0
        for wal in self.primary_root.glob("*.wal"):
            size = wal.stat().st_size
            off = self._offsets.get(wal.stem, 0)
            lag += max(0, size - off)
        return lag

    # -- failover -------------------------------------------------------------

    def promote(self, durable_writes: bool = True) -> DocumentStore:
        """Open the replica directory as a WRITABLE store — the
        failover step.  The caller must stop syncing from the old
        primary first (a promoted replica is a new primary)."""
        self.sync()
        return DocumentStore(
            self.replica_root, durable_writes=durable_writes
        )
