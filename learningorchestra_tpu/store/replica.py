"""WAL-shipping read replica for the document store.

The reference deploys a 3-node MongoDB replica set for persistence HA
(reference: docker-compose.yml:42-90 — mongo + two mongo-secondary
replicas behind a replSetInitiate).  The store here is a per-collection
JSONL write-ahead log (document_store.py), which makes replication a
byte-shipping problem instead of a protocol: a follower tails each
``<name>.wal``, appends the complete records to its OWN copy (fsync'd —
the replica must survive its own crash), and applies them to a live
read view.  Failover is :meth:`WalReplica.promote`: the replica
directory IS a valid store directory, so promotion is just opening it
for writes.

Transports
----------

The mongo secondaries replicate **over the wire** — independent nodes,
independent disks.  Shipping is therefore abstracted behind a transport
with two implementations:

- :class:`FsWalTransport` — reads the primary's store directory through
  the filesystem (shared mount / same host), the original deployment.
- :class:`HttpWalTransport` — pulls WAL byte-ranges from the primary's
  ``/replication`` routes (api/server.py), so a standby on a different
  host with its own disk replicates exactly like a mongo secondary.

Both raise :class:`ReplicationUnavailable` (an ``OSError``) when the
primary cannot be reached, and both are **fail-safe about absence**: a
primary whose store directory is missing, unmounted, or unreadable is a
sync FAILURE, never an instruction to delete replicated data.

Semantics:

- **Record-aligned shipping.**  Only byte ranges ending in a complete
  ``\\n``-terminated record ship; a torn tail on the primary (crash
  mid-append) is never copied, mirroring the primary's own recovery.
- **Compaction/rewrite detection.**  ``compact()`` rewrites a WAL in
  place; the follower detects the file shrinking below its shipped
  offset and resyncs that collection from byte 0 (same for a dropped
  and recreated collection).
- **Drop propagation is positive-evidence-only.**  A collection
  disappears from the replica only when a *successful, non-empty*
  listing of the primary omits it.  An unreachable or empty primary
  root (unmounted network mount, empty mountpoint at boot) must not be
  read as "everything was dropped" — that failure mode would otherwise
  wipe the replica and promote an empty store.
- **Pull model.**  ``sync()`` is explicit — call it on a timer, or
  from a cron/sidecar.  The primary needs no cooperation beyond its
  ordinary appends over the filesystem transport, and only the
  stateless ``/replication`` read routes over HTTP.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path

from learningorchestra_tpu import faults
from learningorchestra_tpu.store.document_store import (
    DocumentStore,
    _match,
)

#: Marker a promotion writes into the OLD primary's store dir.
FENCE_FILE = ".fenced"

#: Election-term file inside a store directory (mongo's replica-set
#: term).  Promotions bump it; a node whose peer serves a HIGHER epoch
#: knows it is the stale side of a healed partition.
EPOCH_FILE = ".epoch"


def read_epoch(store_root: str | Path) -> int:
    """The store's election epoch; 0 for a never-promoted store."""
    try:
        return int((Path(store_root) / EPOCH_FILE).read_text())
    except (OSError, ValueError):
        return 0


def write_epoch(store_root: str | Path, epoch: int) -> None:
    root = Path(store_root)
    root.mkdir(parents=True, exist_ok=True)
    (root / EPOCH_FILE).write_text(str(int(epoch)))


class ReplicationUnavailable(OSError):
    """The primary's WALs cannot be reached right now.

    Subclasses OSError so callers' existing transient-failure handling
    (StandbyMonitor.step keeps probing; promote ships best-effort)
    applies unchanged.
    """


class FsWalTransport:
    """Read the primary's WALs through the filesystem (shared mount)."""

    def __init__(self, primary_root: str | Path):
        self.primary_root = Path(primary_root)

    def list_wals(self) -> list[tuple[str, int]]:
        if not self.primary_root.is_dir():
            raise ReplicationUnavailable(
                f"primary store directory {self.primary_root} is "
                "missing or not a directory"
            )
        out = []
        for wal in sorted(self.primary_root.glob("*.wal")):
            try:
                out.append((wal.stem, wal.stat().st_size))
            except OSError:
                continue  # dropped between glob and stat
        return out

    def read(self, name: str, offset: int,
             length: int | None = None) -> bytes:
        try:
            with open(self.primary_root / f"{name}.wal", "rb") as fh:
                fh.seek(offset)
                return fh.read() if length is None else fh.read(length)
        except FileNotFoundError:
            return b""  # dropped between listing and read

    def epoch(self) -> int:
        return read_epoch(self.primary_root)

    def fence(self, record: dict) -> None:
        self.primary_root.mkdir(parents=True, exist_ok=True)
        (self.primary_root / FENCE_FILE).write_text(json.dumps(record))

    def __repr__(self) -> str:
        return f"FsWalTransport({self.primary_root})"


class HttpWalTransport:
    """Pull WAL byte-ranges from the primary's ``/replication`` routes.

    The network half of the mongo-secondary story (reference:
    docker-compose.yml:42-90 — replication rides the overlay network,
    no shared volume).  The primary serves:

    - ``GET  /replication/wals``                  — listing + epoch
    - ``GET  /replication/wal/<name>?from=&len=`` — raw byte range
    - ``POST /replication/fence``                 — fence + self-demote

    The epoch piggybacks on every listing so the standby still knows
    the primary's last term after the primary dies — promotion bumps
    from the cached value.
    """

    #: Bytes per range request when draining an unbounded read.
    CHUNK = 8 << 20

    def __init__(self, primary_addr: str,
                 prefix: str = "/api/learningOrchestra/v1",
                 timeout: float = 5.0):
        addr = primary_addr
        if not addr.startswith(("http://", "https://")):
            addr = f"http://{addr}"
        self.base = addr.rstrip("/") + prefix + "/replication"
        self.timeout = timeout
        self._epoch = 0

    def list_wals(self) -> list[tuple[str, int]]:
        try:
            with urllib.request.urlopen(
                self.base + "/wals", timeout=self.timeout
            ) as resp:
                payload = json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise ReplicationUnavailable(
                f"primary replication endpoint unreachable: {exc}"
            ) from exc
        self._epoch = int(payload.get("epoch", 0))
        return [
            (w["name"], int(w["size"]))
            for w in payload.get("wals", [])
        ]

    def read(self, name: str, offset: int,
             length: int | None = None) -> bytes:
        if length is not None:
            return self._read_range(name, offset, length)
        out = bytearray()
        while True:
            chunk = self._read_range(
                name, offset + len(out), self.CHUNK
            )
            out += chunk
            if len(chunk) < self.CHUNK:
                return bytes(out)

    def _read_range(self, name: str, offset: int, length: int) -> bytes:
        url = (
            f"{self.base}/wal/{urllib.parse.quote(name)}"
            f"?from={int(offset)}&len={int(length)}"
        )
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as r:
                return r.read()
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return b""  # dropped between listing and read
            raise ReplicationUnavailable(
                f"replication read failed: HTTP {exc.code}"
            ) from exc
        except (urllib.error.URLError, OSError) as exc:
            raise ReplicationUnavailable(
                f"primary replication endpoint unreachable: {exc}"
            ) from exc

    def epoch(self) -> int:
        """Last epoch observed on a listing — survives primary death."""
        return self._epoch

    def fence(self, record: dict) -> None:
        req = urllib.request.Request(
            self.base + "/fence",
            method="POST",
            data=json.dumps(record).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
        except (urllib.error.URLError, OSError) as exc:
            raise ReplicationUnavailable(
                f"could not deliver fence to primary: {exc}"
            ) from exc

    def __repr__(self) -> str:
        return f"HttpWalTransport({self.base})"


def make_transport(primary) -> FsWalTransport | HttpWalTransport:
    """Path-like → filesystem shipping; address/URL → network shipping.

    A string counts as an address when it is an ``http(s)://`` URL or a
    ``host:port`` pair whose suffix is numeric — anything else (including
    plain relative paths) is a directory.
    """
    if hasattr(primary, "list_wals"):
        return primary
    if isinstance(primary, str):
        if primary.startswith(("http://", "https://")):
            return HttpWalTransport(primary)
        # host:port only when the host part is unambiguous — a plain
        # name/IPv4 or a bracketed IPv6 literal.  A bare IPv6 address
        # whose last group is decimal must not be misread as
        # host:port (use "[::1]:8080" to address an IPv6 primary).
        # (Kept in sync by hand with client.Context._make_base — the
        # client stays import-free so it can be vendored standalone.)
        host, _, port = primary.rpartition(":")
        unambiguous = ":" not in host or (
            host.startswith("[") and host.endswith("]")
        )
        if host and port.isdigit() and unambiguous and (
            "/" not in primary
        ):
            return HttpWalTransport(primary)
    return FsWalTransport(primary)


class WalReplica:
    """Read-only follower of a primary store, over either transport."""

    def __init__(self, primary, replica_root: str | Path):
        self.transport = make_transport(primary)
        self.replica_root = Path(replica_root)
        self.replica_root.mkdir(parents=True, exist_ok=True)
        self._offsets: dict[str, int] = {}
        self._docs: dict[str, dict[int, dict]] = {}
        # Bootstrap from whatever the replica dir already holds (a
        # follower restarting must not re-apply from zero into
        # duplicated state — offsets persist next to the shipped WALs).
        for wal in sorted(self.replica_root.glob("*.wal")):
            name = wal.stem
            self._offsets[name] = wal.stat().st_size
            self._docs[name] = {}
            self._apply_bytes(name, wal.read_bytes())

    # -- shipping -------------------------------------------------------------

    def sync(self, *, allow_drops: bool = True) -> dict:
        """Ship new complete records for every primary collection;
        returns {collection: bytes_shipped}.

        Raises :class:`ReplicationUnavailable` when the primary cannot
        be listed — distinguishing "primary gone" (keep everything,
        retry later) from "collection dropped" (mirror the drop).
        ``allow_drops=False`` additionally suppresses drop propagation
        for the final pre-promotion sync: a promote must never delete
        replicated data, whatever the dying primary looks like.
        """
        # Chaos probe: an injected `error` here models the standby
        # crashing mid-ship (its supervisor restarts it; shipped
        # offsets are durable, so the next sync resumes); `delay`
        # models replication lag — the kill-9 recovery drills run
        # their WAL shipping under seeded schedules.
        faults.hit("replica.wal_ship")
        listing = self.transport.list_wals()
        shipped: dict[str, int] = {}
        seen = set()
        for name, size in listing:
            seen.add(name)
            shipped[name] = self._sync_one(name, size)
        # Collections dropped on the primary disappear here too —
        # otherwise a promote would resurrect deleted data.  Only a
        # successful NON-EMPTY listing is evidence of a drop: an empty
        # one is indistinguishable from an unpopulated mountpoint, and
        # acting on it would wipe the replica in exactly the
        # primary-disk-gone failure mode HA exists to survive.
        if allow_drops and listing:
            for name in list(self._offsets):
                if name not in seen:
                    self._offsets.pop(name, None)
                    self._docs.pop(name, None)
                    dst = self.replica_root / f"{name}.wal"
                    if dst.exists():
                        dst.unlink()
        return shipped

    # Shipped-tail window compared against the primary on every sync:
    # detects a COMPACTED-then-REGROWN WAL whose size passed our offset
    # again (size alone can't) — mid-record shipping would silently
    # diverge the replica.
    TAIL_CHECK = 64

    def _sync_one(self, name: str, size: int) -> int:
        offset = self._offsets.get(name, 0)
        rewritten = size < offset
        if not rewritten and offset > 0:
            # Same-or-larger size: confirm the primary still holds the
            # bytes we shipped by comparing the tail window.
            dst = self.replica_root / f"{name}.wal"
            check = min(self.TAIL_CHECK, offset)
            primary_tail = self.transport.read(
                name, offset - check, check
            )
            if len(primary_tail) < check:
                # The file shrank or vanished between the listing and
                # this read (unmounting mid-sync, rmtree, drop race).
                # That is an INCONSISTENT SNAPSHOT, not a compaction:
                # misreading it as a rewrite would clear the replica's
                # copy — the data-loss path the listing guard exists
                # to block.  Fail the sync; the next listing tells the
                # truth.
                raise ReplicationUnavailable(
                    f"{name}.wal shrank below its listed size "
                    "mid-sync — primary snapshot inconsistent"
                )
            with open(dst, "rb") as fh:
                fh.seek(offset - check)
                replica_tail = fh.read(check)
            rewritten = primary_tail != replica_tail
        if rewritten:
            # Compaction (or drop+recreate) rewrote the file: restart
            # this collection from byte 0.
            offset = 0
            self._docs[name] = {}
            dst = self.replica_root / f"{name}.wal"
            if dst.exists():
                dst.unlink()
        data = self.transport.read(name, offset)
        # Ship complete records only: hold back everything past the
        # last newline (a mid-append torn tail must not replicate).
        cut = data.rfind(b"\n")
        if cut < 0:
            return 0
        chunk = data[: cut + 1]
        dst = self.replica_root / f"{name}.wal"
        with open(dst, "ab") as fh:
            fh.write(chunk)
            fh.flush()
            os.fsync(fh.fileno())
        self._offsets[name] = offset + len(chunk)
        self._apply_bytes(name, chunk)
        return len(chunk)

    def _apply_bytes(self, name: str, data: bytes) -> None:
        docs = self._docs.setdefault(name, {})
        for raw in data.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                op = json.loads(raw)
            except ValueError:
                continue  # primary torn tail shipped pre-fix; skip
            kind = op.get("op")
            if kind == "i":
                docs[op["d"]["_id"]] = op["d"]
            elif kind == "u":
                if op["id"] in docs:
                    docs[op["id"]].update(op["d"])
            elif kind == "d":
                docs.pop(op["id"], None)

    # -- read surface ---------------------------------------------------------

    def list_collections(self) -> list[str]:
        return sorted(self._docs)

    def count(self, name: str, query: dict | None = None) -> int:
        return len(self.find(name, query))

    def find(self, name: str, query: dict | None = None) -> list[dict]:
        docs = self._docs.get(name, {})
        return [
            dict(d) for _id, d in sorted(docs.items())
            if _match(d, query)
        ]

    def find_one(self, name: str, _id: int) -> dict | None:
        doc = self._docs.get(name, {}).get(_id)
        return dict(doc) if doc is not None else None

    def lag_bytes(self) -> int:
        """Total unshipped primary bytes — the replication-lag gauge."""
        lag = 0
        for name, size in self.transport.list_wals():
            lag += max(0, size - self._offsets.get(name, 0))
        return lag

    # -- failover -------------------------------------------------------------

    def promote(self, durable_writes: bool = True) -> DocumentStore:
        """Open the replica directory as a WRITABLE store — the
        failover step.  The caller must stop syncing from the old
        primary first (a promoted replica is a new primary).  The
        final sync is best-effort (the primary is usually dead) and
        never deletes replicated data."""
        try:
            self.sync(allow_drops=False)
        except OSError:
            pass
        return DocumentStore(
            self.replica_root, durable_writes=durable_writes
        )
