"""Structured, leveled logging for the framework.

The reference logs with bare ``print(..., flush=True)`` scattered through
hot paths (reference: binary_executor_image/server.py:34,40,
binary_execution.py:242-258 — some in Portuguese); round 1 inherited
that.  This module gives every component one leveled logger with a
single-line structured format::

    2026-07-29T12:00:00 INFO lo.jobs job=mnist_fit state=finished dt=3.2s

Durable observability stays in the execution ledger (store/artifacts.py
— every job's parameters/exception/stdout are persisted as documents,
SURVEY §5.5); the logger is the live, leveled stream next to it.

``LO_TPU_LOG_LEVEL`` sets the level (default INFO).
"""

from __future__ import annotations

import contextlib
import io
import logging
import os
import sys
import threading

from learningorchestra_tpu.concurrency_rt import make_lock

_ROOT = "lo"
_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    root = logging.getLogger(_ROOT)
    if not root.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s",
            datefmt="%Y-%m-%dT%H:%M:%S",
        ))
        root.addHandler(handler)
    level = os.environ.get("LO_TPU_LOG_LEVEL", "INFO").upper()
    root.setLevel(getattr(logging, level, logging.INFO))
    root.propagate = False
    _configured = True


def get_logger(component: str) -> logging.Logger:
    """Logger for a component, namespaced under the framework root
    (``get_logger("jobs")`` → ``lo.jobs``)."""
    _configure()
    return logging.getLogger(f"{_ROOT}.{component}")


def kv(**fields) -> str:
    """Format key=value pairs consistently for log lines."""
    return " ".join(f"{k}={v}" for k, v in fields.items())


class _StdoutRouter(io.TextIOBase):
    """Per-thread stdout demultiplexer.

    ``contextlib.redirect_stdout`` swaps ``sys.stdout`` PROCESS-wide:
    in the multithreaded job engine a captured job steals every other
    thread's prints for its duration (including the embedding
    application's).  The router keeps one real stream and sends each
    write to the calling thread's registered buffer, if any.
    """

    def __init__(self, real):
        self.real = real
        self.buffers: dict[int, io.StringIO] = {}

    def write(self, s):  # hot path: one dict probe
        return self.buffers.get(
            threading.get_ident(), self.real
        ).write(s)

    def flush(self):
        self.buffers.get(threading.get_ident(), self.real).flush()

    def writable(self):
        return True


_router_lock = make_lock("log._router_lock")


@contextlib.contextmanager
def capture_thread_stdout():
    """Capture THIS thread's stdout into a StringIO; other threads keep
    printing to the real stream.  Yields the buffer.

    Installs the router on ``sys.stdout`` on first use and uninstalls
    when the last capture exits, so test harnesses that swap stdout
    themselves (pytest capsys) see their own stream between jobs.

    Scope trade-off: only the registering thread is captured — prints
    from threads a job spawns internally pass through to the real
    stream.  The process-wide alternative mis-attributes EVERY
    concurrent thread's output to whichever job holds the redirect,
    which is strictly worse in a threaded job engine.
    """
    buf = io.StringIO()
    tid = threading.get_ident()
    with _router_lock:
        router = sys.stdout
        if not isinstance(router, _StdoutRouter):
            router = _StdoutRouter(sys.stdout)
            sys.stdout = router
        prev = router.buffers.get(tid)  # nesting: restore on exit
        router.buffers[tid] = buf
    try:
        yield buf
    finally:
        with _router_lock:
            if prev is not None:
                router.buffers[tid] = prev
            else:
                router.buffers.pop(tid, None)
            if not router.buffers and sys.stdout is router:
                sys.stdout = router.real
