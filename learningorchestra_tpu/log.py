"""Structured, leveled logging for the framework.

The reference logs with bare ``print(..., flush=True)`` scattered through
hot paths (reference: binary_executor_image/server.py:34,40,
binary_execution.py:242-258 — some in Portuguese); round 1 inherited
that.  This module gives every component one leveled logger with a
single-line structured format::

    2026-07-29T12:00:00 INFO lo.jobs job=mnist_fit state=finished dt=3.2s

Durable observability stays in the execution ledger (store/artifacts.py
— every job's parameters/exception/stdout are persisted as documents,
SURVEY §5.5); the logger is the live, leveled stream next to it.

``LO_TPU_LOG_LEVEL`` sets the level (default INFO).
"""

from __future__ import annotations

import logging
import os
import sys

_ROOT = "lo"
_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    root = logging.getLogger(_ROOT)
    if not root.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s",
            datefmt="%Y-%m-%dT%H:%M:%S",
        ))
        root.addHandler(handler)
    level = os.environ.get("LO_TPU_LOG_LEVEL", "INFO").upper()
    root.setLevel(getattr(logging, level, logging.INFO))
    root.propagate = False
    _configured = True


def get_logger(component: str) -> logging.Logger:
    """Logger for a component, namespaced under the framework root
    (``get_logger("jobs")`` → ``lo.jobs``)."""
    _configure()
    return logging.getLogger(f"{_ROOT}.{component}")


def kv(**fields) -> str:
    """Format key=value pairs consistently for log lines."""
    return " ".join(f"{k}={v}" for k, v in fields.items())
