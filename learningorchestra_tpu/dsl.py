"""The request-parameter DSL.

The reference rewrites request JSON values before calling toolkit methods
(``Parameters.treat``, duplicated across four services — reference:
microservices/binary_executor_image/binary_execution.py:13-97,
database_executor_image/database_execution.py:8-89, model_image/model.py:8-89,
code_executor_image/code_execution.py:24-105):

- ``"$name"``   → load artifact ``name`` (dataset collection → DataFrame, or
  volume binary);
- ``"$name.key"`` → load artifact then index ``instance[key]``;
- ``"#<python expr>"`` → **exec** the string and pass the resulting object
  (used for optimizers, layers, callbacks).

This framework keeps the ``$`` forms verbatim and re-scopes ``#``: instead
of arbitrary ``exec`` inside the service process, a ``#`` value is a Python
*expression* evaluated with no builtins against a whitelisted namespace of
framework modules (optax, flax.linen, jax.numpy, numpy, the model zoo and
estimator registry).  That covers the reference's real uses —
``#optax.adam(1e-3)``, ``#nn.relu``, ``#[nn.Dense(128), nn.relu]`` — while
the truly-arbitrary-code contract lives only in the ``function/python``
service (SURVEY §7 "hard parts": the exec boundary is design, not code).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Callable, Protocol

_DOLLAR_RE = re.compile(r"^\$(?P<name>[A-Za-z0-9_.\-]+)$")

# The ``#`` grammar is expressions built from calls, attributes, names,
# literals and simple arithmetic — everything an optimizer/layer/callback
# spec needs, nothing more.  Comprehensions, lambdas, f-strings, walrus,
# boolean short-circuits etc. are rejected up front.
_ALLOWED_NODES = (
    ast.Expression, ast.Call, ast.Attribute, ast.Name, ast.Load,
    ast.Constant, ast.List, ast.Tuple, ast.Dict, ast.keyword,
    ast.UnaryOp, ast.UAdd, ast.USub,
    ast.BinOp, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Pow,
    ast.FloorDiv, ast.Mod,
    ast.Subscript, ast.Slice,
)

# File/OS-touching attribute names denied at EVERY level of an attribute
# chain: the namespace roots are whole modules (np, jnp, ...) whose
# numeric surface is wanted but whose IO surface is not — e.g.
# ``#np.load('/etc/passwd')`` (VERDICT r1 weak item 7).
_DENIED_ATTRS = frozenset({
    "load", "loads", "save", "savez", "savez_compressed", "dump",
    "loadtxt", "savetxt", "genfromtxt", "fromfile", "tofile", "memmap",
    "open", "open_memmap", "ctypeslib", "f2py", "distutils", "testing",
    "os", "sys", "subprocess", "importlib", "builtins", "eval", "exec",
    "compile", "getattr", "setattr", "delattr",
})


def _validate_spec(expr: str, allowed_roots: frozenset[str]) -> None:
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError as exc:
        raise DSLResolutionError(
            f"spec {expr!r} does not parse: {exc}"
        ) from exc
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise DSLResolutionError(
                f"spec {expr!r} rejected: "
                f"{type(node).__name__} is not allowed"
            )
        if isinstance(node, ast.Name) and node.id not in allowed_roots:
            raise DSLResolutionError(
                f"spec {expr!r} rejected: unknown name {node.id!r}"
            )
        if isinstance(node, ast.Attribute) and (
            node.attr in _DENIED_ATTRS
        ):
            raise DSLResolutionError(
                f"spec {expr!r} rejected: attribute {node.attr!r} "
                f"is not allowed"
            )


class ArtifactLoader(Protocol):
    """How the DSL turns ``$name`` into an object.  Implemented by the
    service layer over the store + volumes."""

    def load(self, name: str) -> Any: ...


class DSLResolutionError(Exception):
    pass


def _spec_namespace() -> dict:
    """Whitelisted namespace for ``#`` expressions.  Imports are local so
    the DSL module stays importable without JAX for host-only tooling."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import linen as nn

    from learningorchestra_tpu import models as zoo
    from learningorchestra_tpu.toolkit import registry

    ns: dict[str, Any] = {
        "jax": jax,
        "jnp": jnp,
        "np": np,
        "numpy": np,
        "optax": optax,
        "nn": nn,
        "zoo": zoo,
        "True": True,
        "False": False,
        "None": None,
    }
    # Every registered estimator/model constructor is addressable by its
    # class name, e.g. "#LogisticRegression(max_iter=50)".
    ns.update(registry.constructors())
    return ns


def evaluate_spec(expr: str, extra_namespace: dict | None = None) -> Any:
    """Evaluate a ``#`` spec expression against the whitelisted namespace.

    The reference's equivalent rewrites ``#x = <code>`` into
    ``class_instance = <code>`` and ``exec``s it
    (binary_execution.py:59-72); here it is a single expression with
    ``__builtins__`` stripped.
    """
    if "__" in expr:
        # Dunder access would let a spec walk ().__class__.__mro__ out of
        # the sandbox; no legitimate optimizer/layer spec needs it.
        raise DSLResolutionError(
            f"spec {expr!r} rejected: double underscores are not allowed"
        )
    ns = _spec_namespace()
    if extra_namespace:
        ns.update(extra_namespace)
    # AST gate first: only call/attribute/literal expressions over the
    # whitelisted roots, with IO-surface attributes denied everywhere.
    _validate_spec(expr, frozenset(ns))
    try:
        return eval(expr, {"__builtins__": {}}, ns)  # noqa: S307
    except Exception as exc:
        raise DSLResolutionError(
            f"cannot evaluate spec {expr!r}: {exc!r}"
        ) from exc


def resolve_value(
    value: Any,
    loader: ArtifactLoader,
    spec_namespace: dict | None = None,
) -> Any:
    """Resolve one request-JSON value per the DSL rules.

    Mirrors ``Parameters.treat``: strings starting with ``$`` load
    artifacts, ``$name.key`` indexes into the loaded object, ``#`` evaluates
    a spec; lists and dicts resolve element-wise
    (binary_execution.py:26-31 treats lists; dicts are an extension so
    nested kwargs like ``{"optimizer": "#optax.adam(1e-3)"}`` work).
    """
    if isinstance(value, str):
        if value.startswith("$"):
            body = value[1:]
            if not _DOLLAR_RE.match(value):
                raise DSLResolutionError(f"bad artifact reference {value!r}")
            if "." in body:
                # Names may legitimately contain dots ("titanic.csv"), so
                # prefer the whole body as an artifact name and only fall
                # back to the reference's name.key split
                # (binary_executor_image/utils.py:332-336) if that misses.
                try:
                    return loader.load(body)
                except KeyError:
                    pass
                name, key = body.split(".", 1)
                instance = loader.load(name)
                return _index(instance, key)
            return loader.load(body)
        if value.startswith("#"):
            return evaluate_spec(value[1:], spec_namespace)
        return value
    if isinstance(value, list):
        return [resolve_value(v, loader, spec_namespace) for v in value]
    if isinstance(value, dict):
        return {
            k: resolve_value(v, loader, spec_namespace)
            for k, v in value.items()
        }
    return value


def resolve_params(
    params: dict | None,
    loader: ArtifactLoader,
    spec_namespace: dict | None = None,
) -> dict:
    if not params:
        return {}
    return {
        k: resolve_value(v, loader, spec_namespace)
        for k, v in params.items()
    }


def _index(instance: Any, key: str) -> Any:
    """``$name.key`` indexing: tuple/list positions by int, mappings and
    DataFrames by key (binary_executor_image/utils.py:332-336)."""
    try:
        if isinstance(instance, (tuple, list)):
            return instance[int(key)]
        return instance[key]
    except Exception as exc:
        raise DSLResolutionError(
            f"cannot index loaded artifact with {key!r}: {exc!r}"
        ) from exc


def split_special_params(
    params: dict | None, special_keys: tuple[str, ...]
) -> tuple[dict, dict]:
    """Split request params into (special, rest) — the pattern the
    distributed path uses to peel ``callbacks``/``rank0callbacks`` off
    training kwargs (binary_execution.py:246-255)."""
    params = dict(params or {})
    special = {k: params.pop(k) for k in special_keys if k in params}
    return special, params
