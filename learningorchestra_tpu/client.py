"""Python client — the framework's equivalent of the reference's
``learning-orchestra-client`` pip package (layer L0, SURVEY §1: separate
``pythonClient`` repo, ``Context(cluster_ip)`` + per-service classes,
reference: README.md:82-93).

Usage::

    from learningorchestra_tpu.client import Context

    ctx = Context("10.0.0.5")           # or full "http://host:port"
    ctx.dataset_csv.insert("iris", "https://.../iris.csv")
    ctx.observe.wait("iris")            # server-side block until finished
    ctx.projection.create("iris_x", "iris", ["sepal_len", "petal_len"])
    ctx.model.create("mlp", module_path="learningorchestra_tpu.models.mlp",
                     class_name="MLPClassifier",
                     class_parameters={"num_classes": 3})
    ctx.train.create("fit1", model_name="mlp",
                     method_parameters={"x": "$iris_x", "y": "$iris.label",
                                        "epochs": 5})
    ctx.observe.wait("fit1", timeout=600)
    ctx.predict.create("pred1", parent_name="fit1",
                       method_parameters={"x": "$iris_x"})

Only the standard library is used (urllib), so the module is trivially
vendorable as a standalone client package.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Any


class ClientError(Exception):
    """HTTP-level failure; carries the server's status and error payload."""

    def __init__(self, status: int, payload: Any):
        self.status = status
        self.payload = payload
        super().__init__(f"HTTP {status}: {payload}")


class Context:
    """Connection to a learningorchestra_tpu cluster."""

    def __init__(self, cluster: str, port: int = 80,
                 prefix: str = "/api/learningOrchestra/v1",
                 failover: str | None = None,
                 request_timeout: float = 330.0,
                 tenant: str | None = None):
        self.base = self._make_base(cluster, port) + prefix
        # Tenant identity for per-tenant fair-share admission
        # (jobs/cluster.py TenantAdmission): sent as X-Tenant on every
        # request; the gateway may answer 429 + Retry-After when this
        # tenant's queued/running quota is exhausted.
        self.tenant = tenant
        # Standby address for automatic store failover (store/ha.py):
        # on a connection-level failure the client retries ONCE against
        # the standby and — mirroring mongo driver re-discovery — keeps
        # talking to it for the rest of the session.  On every repoint
        # the OLD base becomes the new failover target (mongo's
        # retained seed list, ADVICE r5): after a failover ping-pong
        # the session still has a re-discovery path when the node it
        # repointed to later steps down.
        #
        # Retry semantics are EXACTLY-ONCE for completed mutations
        # (mongo retryable writes): every POST/PATCH/DELETE carries an
        # X-Idempotency-Key, the server records the response in the
        # store (which WAL-ships to the standby), and the failover
        # retry replays the recorded response instead of executing
        # twice.  The one ambiguous window is a primary dying MID-
        # handler: the retry then gets an explicit 409 naming the key
        # ("no recorded outcome") — inspect the artifact's state
        # before retrying with a fresh key.
        self._failover_base = (
            self._make_base(failover, port) + prefix if failover else None
        )
        # Per-request socket timeout.  A hung-but-accepting primary
        # (SIGSTOP, black-holed path) must eventually raise so the
        # failover retry can fire; the default sits above the server's
        # 300 s observe long-poll cap (api/server.py observe_wait) so
        # legitimate long polls never trip it.
        self.request_timeout = request_timeout

        self.dataset_csv = _Dataset(self, "csv")
        self.dataset_generic = _Dataset(self, "generic")
        self.dataset_tensor = _TensorDataset(self)
        self.projection = _Projection(self)
        self.text = _TextTransform(self)
        self.data_type = _DataType(self)
        self.transform = _Transform(self, "tensorflow")
        self.transform_sklearn = _Transform(self, "scikitlearn")
        self.histogram = _Histogram(self)
        self.explore = _Explore(self, "tensorflow")
        self.explore_sklearn = _Explore(self, "scikitlearn")
        self.explore_curves = _Curves(self)
        self.model = _Model(self, "tensorflow")
        self.tune = _Executor(self, "tune", "tensorflow")
        self.train = _Executor(self, "train", "tensorflow")
        self.evaluate = _Executor(self, "evaluate", "tensorflow")
        self.predict = _Executor(self, "predict", "tensorflow")
        self.train_distributed = _DistributedTrain(self)
        self.function = _Function(self)
        self.builder = _Builder(self)
        self.monitoring = _Monitoring(self)
        self.observe = _Observe(self)
        self.serve = _Serve(self)
        self.observability = _Observability(self)
        self.faults = _Faults(self)
        self.jobs = _Jobs(self)
        self.cluster = _Cluster(self)

    # -- transport ----------------------------------------------------------

    @staticmethod
    def _make_base(cluster: str, port: int) -> str:
        if cluster.startswith(("http://", "https://")):
            return cluster.rstrip("/")
        if "/" in cluster:
            # Path-bearing cluster string ("gateway:8080/tenant-a"):
            # pass through — any port is embedded, and bracketing
            # would corrupt it.
            return f"http://{cluster}"
        # host:port only when the suffix is numeric AND the host part
        # is unambiguous: a plain name/IPv4 (no colon) or a bracketed
        # IPv6 literal.  Anything else with colons is a bare IPv6
        # address ("::1", "2001:db8:0:0:0:0:0:1") — its last group may
        # be decimal, so it must never be split on the final colon;
        # bracket it and append the default port.  (Kept in sync by
        # hand with store/replica.py make_transport — the client stays
        # import-free so it can be vendored standalone.)
        host, _, maybe_port = cluster.rpartition(":")
        unambiguous = ":" not in host or (
            host.startswith("[") and host.endswith("]")
        )
        if host and maybe_port.isdigit() and unambiguous:
            return f"http://{host}:{maybe_port}"
        if ":" in cluster and not cluster.startswith("["):
            return f"http://[{cluster}]:{port}"
        return f"http://{cluster}:{port}"

    def request(self, verb: str, path: str, body: dict | None = None,
                query: dict | None = None, raw: bool = False):
        """One logical request with ONE bounded backpressure retry: a
        429 (tenant quota, serving queue overflow) carries Retry-After
        — honor it once (capped at 2 s so a misconfigured server can't
        stall the client), then surface the second 429 to the caller.
        A single retry is deliberate: quotas clear when the tenant's
        own jobs finish, so retrying in a loop would just spin against
        our own backlog."""
        try:
            return self._request_routed(verb, path, body, query, raw)
        except ClientError as exc:
            if exc.status != 429:
                raise
            delay = 0.5
            if isinstance(exc.payload, dict):
                try:
                    delay = float(exc.payload.get("retryAfter") or delay)
                except (TypeError, ValueError):
                    pass
            time.sleep(min(max(delay, 0.0), 2.0))
            return self._request_routed(verb, path, body, query, raw)

    def _request_routed(self, verb: str, path: str,
                        body: dict | None = None,
                        query: dict | None = None, raw: bool = False):
        qs = ""
        if query:
            qs = "?" + urllib.parse.urlencode(
                {k: v if isinstance(v, str) else json.dumps(v)
                 for k, v in query.items()}
            )
        # One key per LOGICAL mutation, minted before the first
        # attempt: the failover retry below reuses it, which is what
        # lets the server replay instead of re-execute (mongo's
        # txnNumber in retryable writes).  Only minted when a failover
        # target exists — without one there is no retry path, and the
        # key would cost the server two durable ledger writes per
        # mutation for nothing.
        idem_key = (
            uuid.uuid4().hex
            if verb in ("POST", "PATCH", "DELETE")
            and self._failover_base is not None
            else None
        )
        try:
            return self._one_request(
                self.base, verb, path, qs, body, raw, idem_key
            )
        except urllib.error.HTTPError as exc:
            if exc.code != 503 or self._failover_base is None:
                raise self._client_error(exc) from None
            # 503 from the base with a failover target armed: either a
            # load-shedding gateway, or — after a failover ping-pong —
            # a node that stepped down to MONITORING STANDBY and now
            # answers everything 503 (store/ha.py).  This is mongo's
            # NotWritablePrimary re-discovery moment: probe the other
            # side; only a real answer repoints (sticky), a 503 or
            # connection failure there surfaces the ORIGINAL error.
            original = self._client_error(exc)
            try:
                result = self._one_request(
                    self._failover_base, verb, path, qs, body, raw,
                    idem_key,
                )
            except urllib.error.HTTPError as fexc:
                if fexc.code == 503:
                    fexc.close()
                    raise original from None
                self.base, self._failover_base = self._failover_base, self.base
                raise self._client_error(fexc) from None
            except (urllib.error.URLError, ConnectionError, OSError):
                raise original from None
            if not self._is_standby_answer(result):
                self.base, self._failover_base = (
                    self._failover_base, self.base
                )
            return result
        except (urllib.error.URLError, ConnectionError, OSError) as conn_exc:
            # Connection-level failure (refused/reset/timeout) — NOT an
            # HTTP status.  If a standby was configured, the primary may
            # have died and the standby promoted itself: retry once
            # there, and on success stay repointed.
            if self._failover_base is None:
                raise
            try:
                result = self._one_request(
                    self._failover_base, verb, path, qs, body, raw,
                    idem_key,
                )
            except urllib.error.HTTPError as exc:
                if exc.code == 503:
                    # A MONITORING standby answers everything but its
                    # status route 503 ("not promoted", store/ha.py):
                    # the pair is alive but no election has happened —
                    # surface the PRIMARY's failure and keep the
                    # failover target armed for the next attempt.
                    # (A promoted-but-load-shedding standby also
                    # 503s; not repointing is safe either way — the
                    # next attempt retries through this same path.)
                    exc.close()
                    raise conn_exc from None
                # The standby answered any other HTTP error: it IS
                # alive and promoted — repoint, surface the error
                # as-is.
                self.base, self._failover_base = self._failover_base, self.base
                raise self._client_error(exc) from None
            if not self._is_standby_answer(result):
                self.base, self._failover_base = self._failover_base, self.base
            return result

    @staticmethod
    def _is_standby_answer(result) -> bool:
        """True when a failover-target response proves the node is a
        MONITORING standby, not a promoted primary.

        The one route an unpromoted standby answers 200 is
        ``/replication/status`` (role=standby, store/ha.py); every API
        response is an artifact list or a role-less dict.  Repointing
        the session to a node that serves nothing else would strand it
        until election — return the data, keep the bases as they are.
        """
        return (
            isinstance(result, dict)
            and result.get("role") == "standby"
        )

    def _one_request(self, base, verb, path, qs, body, raw,
                     idem_key=None, timeout=None):
        headers = {"Content-Type": "application/json"}
        if idem_key:
            headers["X-Idempotency-Key"] = idem_key
        if self.tenant:
            headers["X-Tenant"] = self.tenant
        req = urllib.request.Request(
            base + path + qs,
            method=verb,
            data=json.dumps(body).encode() if body is not None else None,
            headers=headers,
        )
        with urllib.request.urlopen(
            req, timeout=timeout or self.request_timeout
        ) as resp:
            data = resp.read()
            if raw:
                return data
            return json.loads(data) if data else {}

    @staticmethod
    def _client_error(exc: urllib.error.HTTPError) -> "ClientError":
        data = exc.read()
        try:
            payload = json.loads(data)
        except Exception:
            payload = data.decode(errors="replace")
        return ClientError(exc.code, payload)

    # -- conveniences over the universal GET/poll path ----------------------

    def replication_status(self, timeout: float = 5.0) -> dict:
        """Both sides of the HA pair in one call — mongo's
        ``rs.status()`` role.  Each entry is the node's
        ``/replication/status`` record (primaries AND monitoring
        standbys answer it, store/ha.py) or ``{"error": ...}``;
        neither query repoints the session.  ``timeout`` is per probe
        and deliberately SHORT — this is the call an operator makes
        while a node is sick, and the session's 330 s long-poll
        budget would turn diagnosis into an 11-minute hang.
        """
        out: dict = {}
        for key, base in (("base", self.base),
                          ("failover", self._failover_base)):
            if base is None:
                continue
            try:
                out[key] = self._one_request(
                    base, "GET", "/replication/status", "", None,
                    False, timeout=timeout,
                )
            except urllib.error.HTTPError as exc:
                exc.close()
                out[key] = {"error": f"HTTP {exc.code}"}
            except (urllib.error.URLError, ConnectionError,
                    OSError) as exc:
                out[key] = {"error": f"unreachable: {exc}"}
        return out

    def metrics(self) -> dict:
        """Gateway metrics: per-route request counts/latencies + the
        timeout/cache budget (the krakend :8090 exporter's role)."""
        return self.request("GET", "/metrics")

    def search(self, service_path: str, name: str, *, query: dict | None = None,
               limit: int = 20, skip: int = 0) -> list[dict]:
        q: dict = {"limit": limit, "skip": skip}
        if query:
            q["query"] = query
        return self.request("GET", f"/{service_path}/{name}", query=q)

    def metadata(self, service_path: str, name: str) -> dict:
        docs = self.search(service_path, name, limit=1)
        return docs[0] if docs else {}


class _Service:
    service_path = ""  # e.g. "dataset/csv"

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def search(self, name: str, **kw) -> list[dict]:
        return self.ctx.search(self.service_path, name, **kw)

    def metadata(self, name: str) -> dict:
        return self.ctx.metadata(self.service_path, name)

    def delete(self, name: str) -> dict:
        return self.ctx.request(
            "DELETE", f"/{self.service_path}/{name}"
        )

    def wait(self, name: str, timeout: float = 120.0) -> dict:
        return _wait(self.ctx, name, timeout)


def _wait(ctx: Context, name: str, timeout: float) -> dict:
    """Block until ``finished`` or ``jobState=failed`` (server-side long
    poll via /observe, looped client-side for arbitrary timeouts)."""
    deadline = time.time() + timeout
    while True:
        remaining = max(1.0, min(30.0, deadline - time.time()))
        meta = ctx.request(
            "GET", f"/observe/{name}", query={"timeout": remaining}
        )["metadata"]
        if meta.get("finished") or meta.get("jobState") == "failed":
            return meta
        if time.time() >= deadline:
            raise TimeoutError(f"artifact {name!r} not finished "
                               f"after {timeout}s: {meta}")


class _Dataset(_Service):
    def __init__(self, ctx: Context, kind: str):
        super().__init__(ctx)
        self.service_path = f"dataset/{kind}"

    def insert(self, dataset_name: str, url: str,
               shard_rows: int | None = None) -> dict:
        """``shard_rows`` switches to sharded (beyond-host-RAM) ingest:
        rows land in columnar volume shards the training paths stream
        (store/sharded.py)."""
        body = {"datasetName": dataset_name, "url": url}
        if shard_rows is not None:
            body["shardRows"] = int(shard_rows)
        return self.ctx.request("POST", f"/{self.service_path}", body)

    def list(self) -> list[dict]:
        return self.ctx.request("GET", f"/{self.service_path}")


class _TensorDataset(_Service):
    """N-D (image-shaped) sharded ingest: features + labels as .npy
    files, memory-mapped and copied shard by shard — the beyond-RAM
    path for BASELINE config 5-style image datasets."""

    service_path = "dataset/tensor"

    def insert(self, dataset_name: str, url: str, labels_url: str,
               shard_rows: int = 4096) -> dict:
        return self.ctx.request("POST", f"/{self.service_path}", {
            "datasetName": dataset_name, "url": url,
            "labelsUrl": labels_url, "shardRows": int(shard_rows),
        })

    def list(self) -> list[dict]:
        return self.ctx.request("GET", f"/{self.service_path}")


class _Projection(_Service):
    service_path = "transform/projection"

    def create(self, projection_name: str, dataset_name: str,
               fields: list[str]) -> dict:
        return self.ctx.request(
            "POST", "/transform/projection",
            {"projectionName": projection_name, "datasetName": dataset_name,
             "fields": fields},
        )

    def update(self, projection_name: str,
               fields: list[str] | None = None) -> dict:
        """PATCH re-run — replaces the projected rows (new ``fields``
        when given, else the original request's)."""
        return self.ctx.request(
            "PATCH", "/transform/projection",
            {"projectionName": projection_name, "fields": fields},
        )


class _TextTransform(_Service):
    """BPE tokenization of a text column into a tensor-sharded dataset
    of fixed-length int32 rows (beyond the reference's surface — its
    text configs assume user preprocessing in compile_code)."""

    service_path = "transform/text"

    def create(self, name: str, dataset_name: str, *, text_field: str,
               label_field: str | None = None, vocab_size: int = 8000,
               max_len: int = 128, lowercase: bool = True,
               tokenizer_from: str | None = None,
               shard_rows: int = 4096) -> dict:
        return self.ctx.request(
            "POST", "/transform/text",
            {"name": name, "datasetName": dataset_name,
             "textField": text_field, "labelField": label_field,
             "vocabSize": vocab_size, "maxLen": max_len,
             "lowercase": lowercase, "tokenizerFrom": tokenizer_from,
             "shardRows": shard_rows},
        )

    def update(self, name: str) -> dict:
        """PATCH re-run — re-tokenizes from the parent's current rows."""
        return self.ctx.request("PATCH", f"/transform/text/{name}", {})


class _Transform(_Service):
    """Generic transform executions (reference: POST/PATCH/DELETE
    /transform/{t} → databaseExecutor, SURVEY §2.2)."""

    def __init__(self, ctx: Context, tool: str):
        super().__init__(ctx)
        self.tool = tool
        self.service_path = f"transform/{tool}"

    def create(self, name: str, *, module_path: str, class_name: str,
               class_parameters: dict | None = None,
               method: str | None = None,
               method_parameters: dict | None = None,
               description: str = "") -> dict:
        return self.ctx.request(
            "POST", f"/transform/{self.tool}",
            {"name": name, "modulePath": module_path, "class": class_name,
             "classParameters": class_parameters or {}, "method": method,
             "methodParameters": method_parameters or {},
             "description": description},
        )

    def update(self, name: str, *,
               class_parameters: dict | None = None,
               method_parameters: dict | None = None,
               description: str = "") -> dict:
        return self.ctx.request(
            "PATCH", f"/transform/{self.tool}/{name}",
            {"classParameters": class_parameters,
             "methodParameters": method_parameters,
             "description": description},
        )


class _DataType(_Service):
    service_path = "transform/dataType"

    def update(self, dataset_name: str, types: dict) -> dict:
        return self.ctx.request(
            "PATCH", "/transform/dataType",
            {"datasetName": dataset_name, "types": types},
        )


class _Histogram(_Service):
    service_path = "explore/histogram"

    def create(self, histogram_name: str, dataset_name: str,
               fields: list[str]) -> dict:
        return self.ctx.request(
            "POST", "/explore/histogram",
            {"histogramName": histogram_name, "datasetName": dataset_name,
             "fields": fields},
        )


class _Curves(_Service):
    """Training-curves PNG from a train artifact's history rows."""

    service_path = "explore/curves"

    def create(self, name: str, train_name: str,
               fields: list[str] | None = None) -> dict:
        return self.ctx.request(
            "POST", "/explore/curves",
            {"name": name, "parentName": train_name, "fields": fields},
        )

    def update(self, name: str) -> dict:
        """PATCH re-run — re-reads the parent's current history."""
        return self.ctx.request("PATCH", f"/explore/curves/{name}", {})

    def image(self, name: str) -> bytes:
        return self.ctx.request("GET", f"/explore/curves/{name}", raw=True)


class _Explore(_Service):
    def __init__(self, ctx: Context, tool: str):
        super().__init__(ctx)
        self.tool = tool
        self.service_path = f"explore/{tool}"

    def create(self, name: str, *, module_path: str, class_name: str,
               class_parameters: dict | None = None,
               method: str = "fit_transform",
               method_parameters: dict | None = None,
               color_by: str | None = None, description: str = "") -> dict:
        return self.ctx.request(
            "POST", f"/explore/{self.tool}",
            {"name": name, "modulePath": module_path, "class": class_name,
             "classParameters": class_parameters or {}, "method": method,
             "methodParameters": method_parameters or {},
             "colorBy": color_by, "description": description},
        )

    def update(self, name: str, *,
               class_parameters: dict | None = None,
               method_parameters: dict | None = None,
               color_by: str | None = None,
               description: str = "") -> dict:
        """PATCH re-run — re-renders the plot."""
        return self.ctx.request(
            "PATCH", f"/explore/{self.tool}/{name}",
            {"classParameters": class_parameters,
             "methodParameters": method_parameters,
             "colorBy": color_by, "description": description},
        )

    def image(self, name: str) -> bytes:
        return self.ctx.request(
            "GET", f"/explore/{self.tool}/{name}", raw=True
        )

    def search(self, name: str, *, query: dict | None = None,
               limit: int = 20, skip: int = 0) -> list[dict]:
        # GET /explore/{tool}/{name} serves the PNG; rows live under the
        # /metadata suffix (reference: krakend.json explore block).
        q: dict = {"limit": limit, "skip": skip}
        if query:
            q["query"] = query
        return self.ctx.request(
            "GET", f"/explore/{self.tool}/{name}/metadata", query=q
        )

    def metadata(self, name: str) -> dict:
        docs = self.search(name, limit=1)
        return docs[0] if docs else {}

    def wait(self, name: str, timeout: float = 120.0) -> dict:
        return _wait(self.ctx, name, timeout)


class _Model(_Service):
    def __init__(self, ctx: Context, tool: str):
        super().__init__(ctx)
        self.tool = tool
        self.service_path = f"model/{tool}"

    def create(self, model_name: str, *, module_path: str, class_name: str,
               class_parameters: dict | None = None,
               description: str = "") -> dict:
        return self.ctx.request(
            "POST", f"/model/{self.tool}",
            {"modelName": model_name, "modulePath": module_path,
             "class": class_name,
             "classParameters": class_parameters or {},
             "description": description},
        )

    def update(self, model_name: str,
               class_parameters: dict | None = None,
               description: str = "") -> dict:
        return self.ctx.request(
            "PATCH", f"/model/{self.tool}/{model_name}",
            {"classParameters": class_parameters, "description": description},
        )


class _Executor(_Service):
    """tune / train / evaluate / predict over a parent artifact."""

    def __init__(self, ctx: Context, service: str, tool: str):
        super().__init__(ctx)
        self.service = service
        self.tool = tool
        self.service_path = f"{service}/{tool}"

    def create(self, name: str, *, parent_name: str | None = None,
               model_name: str | None = None, method: str | None = None,
               method_parameters: dict | None = None,
               param_grid: dict | None = None,
               scoring_parameters: dict | None = None,
               description: str = "",
               deadline_s: float | None = None) -> dict:
        body: dict = {
            "name": name,
            "parentName": parent_name or model_name,
            "modelName": model_name,
            "method": method or ("fit" if self.service in ("train", "tune")
                                 else self.service),
            "methodParameters": method_parameters or {},
            "description": description,
        }
        if param_grid:
            body["paramGrid"] = param_grid
            if scoring_parameters:
                body["scoringParameters"] = scoring_parameters
        if deadline_s is not None:
            # Per-job wall-clock bound: past it the engine watchdog
            # fails the job and reclaims its worker and chip leases
            # (0 disables for this job, None inherits the server's
            # LO_TPU_JOB_DEADLINE_S default).
            body["deadlineS"] = deadline_s
        return self.ctx.request("POST", f"/{self.service_path}", body)

    def update(self, name: str, *, method_parameters: dict | None = None,
               description: str = "",
               deadline_s: float | None = None) -> dict:
        body: dict = {"methodParameters": method_parameters,
                      "description": description}
        if deadline_s is not None:
            body["deadlineS"] = deadline_s
        return self.ctx.request(
            "PATCH", f"/{self.service_path}/{name}", body
        )


class _DistributedTrain(_Service):
    service_path = "train/horovod"

    def create(self, name: str, *, parent_name: str,
               training_parameters: dict,
               compile_spec: dict | None = None,
               mesh: dict | None = None,
               monitoring_path: str | None = None,
               description: str = "") -> dict:
        return self.ctx.request(
            "POST", "/train/horovod",
            {"name": name, "parentName": parent_name,
             "trainingParameters": training_parameters,
             "compile": compile_spec, "mesh": mesh,
             "monitoringPath": monitoring_path,
             "description": description},
        )

    def update(self, name: str, *,
               training_parameters: dict | None = None,
               compile_spec: dict | None = None,
               mesh: dict | None = None,
               description: str = "") -> dict:
        """PATCH re-run; a bare call resumes a failed job with its
        original parameters."""
        return self.ctx.request(
            "PATCH", f"/train/horovod/{name}",
            {"trainingParameters": training_parameters,
             "compile": compile_spec, "mesh": mesh,
             "description": description},
        )


class _Function(_Service):
    service_path = "function/python"

    def create(self, name: str, *, function: str,
               function_parameters: dict | None = None,
               description: str = "",
               deadline_s: float | None = None) -> dict:
        body: dict = {"name": name, "function": function,
                      "functionParameters": function_parameters or {},
                      "description": description}
        if deadline_s is not None:
            body["deadlineS"] = deadline_s
        return self.ctx.request("POST", "/function/python", body)

    def update(self, name: str, *, function: str | None = None,
               function_parameters: dict | None = None,
               description: str = "",
               deadline_s: float | None = None) -> dict:
        body: dict = {"function": function,
                      "functionParameters": function_parameters,
                      "description": description}
        if deadline_s is not None:
            body["deadlineS"] = deadline_s
        return self.ctx.request(
            "PATCH", f"/function/python/{name}", body
        )


class _Builder(_Service):
    service_path = "builder/sparkml"

    def create(self, *, train_dataset: str, test_dataset: str,
               classifiers: list[str], label_field: str = "label",
               feature_fields: list[str] | None = None,
               modeling_code: str | None = None,
               classifier_parameters: dict | None = None,
               description: str = "") -> dict:
        """Whole-pipeline builder (reference: POST /builder/sparkml)."""
        return self.ctx.request(
            "POST", "/builder/sparkml",
            {"trainDatasetName": train_dataset,
             "testDatasetName": test_dataset,
             "classifiersList": classifiers, "labelField": label_field,
             "featureFields": feature_fields,
             "modelingCode": modeling_code,
             "classifierParameters": classifier_parameters,
             "description": description},
        )

    def create_distributed(self, name: str, *, function: str,
                           function_parameters: dict | None = None,
                           n_workers: int | None = None,
                           description: str = "") -> dict:
        """One user function on every rank (reference: POST
        /builder/tensorflow|pytorch → builder/horovod)."""
        return self.ctx.request(
            "POST", "/builder/tensorflow",
            {"name": name, "function": function,
             "functionParameters": function_parameters or {},
             "nWorkers": n_workers, "description": description},
        )


class _Monitoring:
    """Session registry lookups — NOT an artifact service (its GET
    returns a session dict, not document rows)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def lookup(self, nickname: str) -> dict:
        return self.ctx.request(
            "GET", f"/monitoring/tensorflow/{nickname}"
        )

    def list(self) -> list[dict]:
        return self.ctx.request("GET", "/monitoring/tensorflow")

    def stop(self, nickname: str) -> dict:
        return self.ctx.request(
            "DELETE", f"/monitoring/tensorflow/{nickname}"
        )


class _Serve:
    """Resident model serving — the synchronous low-latency surface
    (POST /serve/<model>/predict + load/unload/list).  Rides the
    Context transport, so failover retry/repoint applies unchanged;
    a 429 (queue overflow) surfaces as ``ClientError(429, ...)`` whose
    payload carries ``retryAfter`` seconds."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def predict(self, model: str, instances) -> dict:
        """Synchronous predict: ``instances`` is one feature vector or
        a list of them; returns ``{"predictions": [...], ...}`` in the
        response — no job, no polling."""
        return self.ctx.request(
            "POST", f"/serve/{model}/predict", {"instances": instances}
        )

    def generate(self, model: str, prompts, *,
                 max_new_tokens: int = 32, stream: bool = False,
                 temperature: float | None = None,
                 top_k: int | None = None, top_p: float | None = None,
                 seed: int = 0, timeout: float | None = None):
        """Autoregressive decode against a resident LM.

        Non-stream (default): POST /serve/<model>/generate, returns
        the full ``{"tokens": [[...]], "newTokens": [[...]], ...}``
        response.  With ``stream=True`` (single prompt only) the call
        returns a GENERATOR of ``(event, doc)`` pairs parsed from the
        server's ``text/event-stream`` body — ``("open", ...)``, then
        one ``("token", {"t": id, "i": pos})`` per generated token,
        terminated by ``("done", summary)`` / ``("error", ...)`` /
        ``("aborted", ...)``.  Closing the generator drops the socket,
        which the server treats as a client abort (KV pages freed at
        the next decode step)."""
        body: dict = {
            "prompts": prompts,
            "maxNewTokens": int(max_new_tokens),
            "seed": int(seed),
        }
        if temperature is not None:
            body["temperature"] = temperature
        if top_k is not None:
            body["topK"] = top_k
        if top_p is not None:
            body["topP"] = top_p
        if not stream:
            return self.ctx.request(
                "POST", f"/serve/{model}/generate", body
            )
        body["stream"] = True
        return self._sse_events(
            f"/serve/{model}/generate", body, timeout
        )

    def _sse_events(self, path: str, body: dict,
                    timeout: float | None):
        """Minimal SSE line parser over the streaming decode body:
        accumulates ``event:``/``data:`` fields, yields on each blank
        line.  urllib only — same zero-dependency discipline as the
        rest of the client."""
        req = urllib.request.Request(
            self.ctx.base + path, method="POST",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            resp = urllib.request.urlopen(
                req,
                timeout=timeout or max(
                    self.ctx.request_timeout, 300.0
                ),
            )
        except urllib.error.HTTPError as exc:
            raise Context._client_error(exc) from None
        try:
            event: str | None = None
            data_lines: list[str] = []
            for raw in resp:
                line = raw.decode(
                    "utf-8", errors="replace"
                ).rstrip("\r\n")
                if line:
                    if line.startswith("event:"):
                        event = line[len("event:"):].strip()
                    elif line.startswith("data:"):
                        data_lines.append(
                            line[len("data:"):].strip()
                        )
                    continue
                if event is None and not data_lines:
                    continue  # keep-alive blank
                joined = "\n".join(data_lines)
                try:
                    doc = json.loads(joined) if joined else {}
                except json.JSONDecodeError:
                    doc = {"raw": joined}
                yield (event or "message", doc)
                event, data_lines = None, []
        finally:
            resp.close()

    def abort_stream(self, model: str, stream_id: str) -> dict:
        """DELETE /serve/<model>/generate/<stream> — server-side abort
        of an in-flight decode stream (frees its KV slot at the next
        step boundary); 404 when the stream already finished."""
        return self.ctx.request(
            "DELETE", f"/serve/{model}/generate/{stream_id}"
        )

    def load(self, model: str) -> dict:
        """Pin a trained artifact's params resident on device."""
        return self.ctx.request("POST", f"/serve/{model}/load", {})

    def unload(self, model: str) -> dict:
        return self.ctx.request("POST", f"/serve/{model}/unload", {})

    def list_loaded(self) -> dict:
        return self.ctx.request("GET", "/serve")

    def stats(self) -> dict:
        """Serving observability: p50/p95/p99 latency, queue depth,
        batch occupancy, bucket histogram (also appended as
        ``serving_*`` tfevents scalars server-side)."""
        return self.ctx.request(
            "GET", "/monitoring/tensorflow/serving"
        )

    # -- fleet (multi-replica data plane + autoscaler) ------------------

    def replicas(self, model: str) -> dict:
        """GET /serve/<model>/replicas — the model's replica set:
        per-replica device LIST (multi-chip replicas lease a slice)
        and shard spec, queue depth, request counts, plus the min/max
        autoscaler bounds and chips-per-replica; 404 until a set
        exists."""
        return self.ctx.request("GET", f"/serve/{model}/replicas")

    def scale(self, model: str, *, count: int | None = None,
              min_replicas: int | None = None,
              max_replicas: int | None = None,
              devices_per_replica: int | None = None) -> dict:
        """POST /serve/<model>/replicas — create/resize the model's
        replica set: ``min``/``max`` set the autoscaler bounds,
        ``count`` scales manually (clamped to the bounds),
        ``devices_per_replica`` sets the chips each replica leases
        (> 1 shards the params across the slice for models bigger
        than one chip; fixed while the set is live).  Each replica
        pins its chips through the lease pool; an exhausted pool
        surfaces as 503 + Retry-After."""
        body: dict = {}
        if count is not None:
            body["count"] = count
        if min_replicas is not None:
            body["min"] = min_replicas
        if max_replicas is not None:
            body["max"] = max_replicas
        if devices_per_replica is not None:
            body["devicesPerReplica"] = devices_per_replica
        return self.ctx.request(
            "POST", f"/serve/{model}/replicas", body
        )

    def dissolve(self, model: str) -> dict:
        """DELETE /serve/<model>/replicas — drain the model's fleet
        and return it to classic single-path serving (chips released,
        model stays loaded; deployment-wide fleet defaults won't
        re-fleet it)."""
        return self.ctx.request(
            "DELETE", f"/serve/{model}/replicas"
        )

    def fleet_status(self) -> dict:
        """GET /serve/fleet — every replica set plus autoscaler state
        (tick counts, per-model streaks, recent scale decisions)."""
        return self.ctx.request("GET", "/serve/fleet")


class _Observability:
    """The unified observability layer (server obs/): Prometheus text
    exposition and per-job trace span trees.  The JSON endpoints the
    other bindings use remain; these are the scrape/trace surfaces."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def metrics_prom(self) -> str:
        """GET /metrics.prom — the whole registry (HTTP latency
        histograms, job queue waits, lease utilization, compile-cache
        counters, serving occupancy, store/replication state) in
        Prometheus text exposition format."""
        return self.ctx.request(
            "GET", "/metrics.prom", raw=True
        ).decode()

    def trace(self, name: str) -> dict:
        """GET /observability/jobs/<name>/trace — the job's span tree
        (queue wait → lease → compile → per-epoch steps) with the
        request id the submission carried; 404 until a completed run
        has recorded one."""
        return self.ctx.request(
            "GET", f"/observability/jobs/{name}/trace"
        )

    def costs(self) -> dict:
        """GET /observability/costs — the cost-accounting plane: per-
        program FLOPs/HBM records and the device-time ledgers (per
        job / per served model / per serving bucket, with MFU when
        the server configured its chips' peak FLOP/s)."""
        return self.ctx.request("GET", "/observability/costs")

    def locks(self) -> dict:
        """GET /observability/locks — the runtime lock witness's
        deadlock-diagnosis dump (LO_TPU_WITNESS=1): witnessed
        acquisition-order edges, held-while-blocking events, and
        every held/contended lock with holder, waiters and live
        thread stacks."""
        return self.ctx.request("GET", "/observability/locks")

    # -- windowed rollups + SLO alerting --------------------------------

    def timeseries(self, name: str | None = None,
                   window_s: float | None = None,
                   points: int | None = None,
                   **labels) -> dict:
        """GET /observability/timeseries — the rollup engine's
        windowed view of one registry family: raw ring points plus
        the derived rate (counters), min/avg/max + slope (gauges) or
        bucket-delta quantiles (histograms).  Label kwargs filter
        series (``timeseries("lo_serving_model_queue_depth",
        model="mnist")``); no ``name`` lists the tracked families."""
        query: dict = dict(labels)
        if name is not None:
            query["name"] = name
        if window_s is not None:
            query["windowS"] = window_s
        if points is not None:
            query["points"] = points
        return self.ctx.request(
            "GET", "/observability/timeseries", query=query
        )

    def alerts(self) -> dict:
        """GET /observability/alerts — live SLO alert states
        (pending/firing/resolved) with the burn rates that produced
        them, plus the bounded transition history and the evaluation
        config."""
        return self.ctx.request("GET", "/observability/alerts")

    def slo(self) -> dict:
        """GET /observability/slo — the declarative objectives with
        their targets, error budgets, live fast/slow burn rates and
        budget remaining per instance."""
        return self.ctx.request("GET", "/observability/slo")

    def slo_create(self, name: str, kind: str, target: float,
                   threshold_ms: float | None = None,
                   metric: str | None = None,
                   route: str | None = None) -> dict:
        """POST /observability/slo — register an ad-hoc runtime
        objective (the drill surface): ``availability`` with an
        optional ``route`` filter (e.g. ``"GET /health"``), or
        ``latency`` with ``threshold_ms`` against a histogram
        ``metric``.  Runtime objectives evaluate on the same rollup
        clock as config-built ones and are removable."""
        body: dict = {"name": name, "kind": kind, "target": target}
        if threshold_ms is not None:
            body["thresholdMs"] = threshold_ms
        if metric is not None:
            body["metric"] = metric
        if route is not None:
            body["route"] = route
        return self.ctx.request("POST", "/observability/slo", body)

    def slo_delete(self, name: str) -> dict:
        """DELETE /observability/slo/<name> — drop a runtime
        objective and its live alert rows (config-built objectives
        are the deployment's contract and answer 404)."""
        return self.ctx.request(
            "DELETE", f"/observability/slo/{name}"
        )

    # -- flight recorder + debug bundles --------------------------------

    def flight(self, domains: list | None = None,
               limit: int | None = None) -> dict:
        """GET /observability/flight — the always-on flight
        recorder's per-domain event rings (http, decode, jobs,
        compile, faults, locks) plus the merged incident
        ``timeline`` ordered by monotonic time."""
        query: dict = {}
        if domains:
            query["domain"] = ",".join(domains)
        if limit is not None:
            query["limit"] = limit
        return self.ctx.request(
            "GET", "/observability/flight", query=query
        )

    def bundle_create(self, reason: str | None = None) -> dict:
        """POST /observability/bundle — assemble a debug bundle NOW
        (synchronous; a concurrent assembly raises ClientError 409).
        Returns the manifest: flight rings, metrics/rollup/SLO/fleet
        snapshots, journal tail, fault + lock state."""
        body = {"reason": reason} if reason else {}
        return self.ctx.request(
            "POST", "/observability/bundle", body
        )

    def bundles(self) -> dict:
        """GET /observability/bundles — the on-disk bundle store:
        retained bundles plus assembler status (built/debounced
        counters, retention knobs)."""
        return self.ctx.request("GET", "/observability/bundles")

    def bundle_get(self, name: str) -> dict:
        """GET /observability/bundles/<name> — one bundle's
        manifest (file list, sizes, trigger reason/detail,
        per-provider errors)."""
        return self.ctx.request(
            "GET", f"/observability/bundles/{name}"
        )

    def bundle_fetch(self, name: str, path: str) -> bytes:
        """One bundle artifact's bytes (e.g. ``flight.json``)."""
        return self.ctx.request(
            "GET", f"/observability/bundles/{name}",
            query={"file": path}, raw=True,
        )

    def bundle_delete(self, name: str) -> dict:
        """DELETE /observability/bundles/<name>."""
        return self.ctx.request(
            "DELETE", f"/observability/bundles/{name}"
        )

    def bundles_clear(self) -> dict:
        """DELETE /observability/bundles — drop every retained
        bundle; returns the count removed."""
        return self.ctx.request("DELETE", "/observability/bundles")

    # -- on-demand profiler capture -------------------------------------

    def profile_start(self, name: str | None = None,
                      max_seconds: float | None = None) -> dict:
        """POST /observability/profile/start — begin a jax.profiler
        capture on the LIVE server (one at a time; a second start
        raises ClientError 409).  Auto-stops after ``max_seconds``
        (clamped to the server's LO_TPU_PROF_MAX_S)."""
        body: dict = {}
        if name is not None:
            body["name"] = name
        if max_seconds is not None:
            body["maxSeconds"] = max_seconds
        return self.ctx.request(
            "POST", "/observability/profile/start", body
        )

    def profile_stop(self) -> dict:
        """POST /observability/profile/stop — end the active capture;
        returns its file manifest."""
        return self.ctx.request(
            "POST", "/observability/profile/stop", {}
        )

    def profile_status(self) -> dict:
        return self.ctx.request("GET", "/observability/profile")

    def profile_captures(self) -> dict:
        """GET /observability/profile/captures — every retained
        capture with its file manifest."""
        return self.ctx.request(
            "GET", "/observability/profile/captures"
        )

    def profile_fetch(self, capture: str, path: str) -> bytes:
        """One capture artifact's bytes (e.g. the ``.xplane.pb`` for
        TensorBoard's profile plugin)."""
        return self.ctx.request(
            "GET", f"/observability/profile/captures/{capture}",
            query={"file": path}, raw=True,
        )


class _Faults:
    """Fault-injection plane (server faults/): arm deterministic,
    seeded chaos schedules against named fault points
    (``engine.dispatch``, ``train.epoch``, ``store.wal_write``, ...)
    and read per-point hit/trigger counters.  The drill surface behind
    the self-healing claims — see README "Fault tolerance"."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def status(self) -> dict:
        """GET /faults — every registered point with its armed
        schedule (if any) and cumulative hit/trigger counts."""
        return self.ctx.request("GET", "/faults")

    def arm(self, point: str, mode: str, *, rate: float = 1.0,
            seed: int = 0, after: int = 0, max_triggers: int = 0,
            delay_ms: float = 0.0) -> dict:
        """Arm ``point`` with a seeded schedule: ``mode`` is
        ``preempt`` (raise the engine's retryable preemption),
        ``error`` (ordinary crash) or ``delay`` (sleep ``delay_ms``);
        ``after`` skips the first N hits, ``max_triggers`` bounds
        total firings, ``rate < 1`` fires a seeded-deterministic
        subset."""
        return self.ctx.request(
            "POST", f"/faults/{point}",
            {"mode": mode, "rate": rate, "seed": seed, "after": after,
             "maxTriggers": max_triggers, "delayMs": delay_ms},
        )

    def disarm(self, point: str) -> dict:
        return self.ctx.request("DELETE", f"/faults/{point}")

    def disarm_all(self) -> dict:
        return self.ctx.request("DELETE", "/faults")


class _Jobs:
    """Job control plane: cooperative cancellation over the journaled
    engine (server jobs/engine.py + jobs/journal.py)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def cancel(self, name: str) -> dict:
        """DELETE /jobs/<name> — cancel a queued job outright
        (``result: cancelled``) or flip a RUNNING job's CancelToken
        (``result: cancelling``, HTTP 202): the body observes it at
        its next epoch/batch boundary, winds down like an early stop,
        and the artifact lands in jobState ``cancelled`` with a
        journaled terminal transition.  409 when the job is already
        terminal."""
        return self.ctx.request("DELETE", f"/jobs/{name}")


class _Cluster:
    """Scale-out control plane (server jobs/cluster.py): engine
    membership, dispatch claims and per-tenant admission counters."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def status(self) -> dict:
        """GET /cluster/status — ``{"enabled", "engines", "claims"[,
        "tenants"]}``.  Single-engine deployments answer 200 with
        ``enabled: false`` rather than 404, so callers never need a
        topology-aware special case."""
        return self.ctx.request("GET", "/cluster/status")


class _Observe:
    """The reference's separate Observe service (collection watch,
    README.md:71) — a server-side long poll (``wait``) plus push
    webhooks on state transitions (``webhook``/``webhooks``/
    ``unwatch``)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    def wait(self, name: str, timeout: float = 120.0) -> dict:
        return _wait(self.ctx, name, timeout)

    def webhook(self, name: str, url: str,
                events: list | None = None) -> dict:
        """Register ``url`` to be POSTed ``{"name", "event",
        "metadata"}`` when ``name`` finishes or fails."""
        body = {"url": url}
        if events is not None:
            body["events"] = list(events)
        return self.ctx.request(
            "POST", f"/observe/{name}/webhook", body
        )["result"]

    def webhooks(self, name: str) -> list:
        return self.ctx.request(
            "GET", f"/observe/{name}/webhook"
        )["result"]

    def unwatch(self, name: str, hook_id: int) -> None:
        self.ctx.request(
            "DELETE", f"/observe/{name}/webhook/{hook_id}"
        )

    def webhook_all(self, url: str, events: list | None = None) -> dict:
        """Wildcard registration: ``url`` fires for EVERY artifact's
        finish/fail — the reference Observe's watch-anything shape."""
        body: dict = {"url": url}
        if events is not None:
            body["events"] = list(events)
        return self.ctx.request("POST", "/observe/webhook", body)["result"]

    def events(self, since_id: int = -1, limit: int = 100) -> list:
        """The global event feed, oldest-first; cursor on the last
        row's ``_id``: ``events(since_id=rows[-1]["_id"])``."""
        return self.ctx.request(
            "GET", "/observe/events",
            query={"sinceId": int(since_id), "limit": int(limit)},
        )["result"]
