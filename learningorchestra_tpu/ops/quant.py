"""Row-wise int8 quantization kernels (Pallas).

Artifact/HBM footprint tool: trained parameter matrices and cached
activations quantize to int8 with one scale per row — 4x smaller than
f32 — and dequantize on load.  On TPU the quantizer uses the on-core
PRNG for stochastic rounding (unbiased: E[q] = x/scale, so repeated
quantize→accumulate steps don't drift the way round-to-nearest does);
off-TPU the same kernels run in interpret mode.

API:
  quantize_rowwise(x)   -> (values int8 (n, d), scales f32 (n, 1))
  dequantize_rowwise(v, s) -> f32 (n, d)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _quantize_kernel(seed_ref, x_ref, values_ref, scales_ref, *, stochastic):
    x = x_ref[:].astype(jnp.float32)
    abs_max = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(abs_max, 1e-12) / 127.0
    scaled = x / scale
    if stochastic:
        # Re-seed per row block so streams stay independent across the
        # grid (every program would otherwise draw identical bits).
        # Multi-word seed: (seed + i) would collide with (seed+1, i-1)
        # when callers seed by step counter.
        pltpu.prng_seed(seed_ref[0], pl.program_id(0))
        bits = pltpu.bitcast(
            pltpu.prng_random_bits(scaled.shape), jnp.uint32
        )
        # Uniform in [0, 1): 23 mantissa bits of the random word.  The
        # shift clears the sign bit, so the int32 hop is lossless —
        # Mosaic has no direct uint32->f32 cast.
        u = (
            (bits >> jnp.uint32(9)).astype(jnp.int32).astype(jnp.float32)
        ) * (1.0 / (1 << 23))
        q = jnp.floor(scaled + u)
    else:
        q = jnp.round(scaled)
    values_ref[:] = jnp.clip(q, -127, 127).astype(jnp.int8)
    scales_ref[:] = scale


def _dequantize_kernel(values_ref, scales_ref, out_ref):
    out_ref[:] = values_ref[:].astype(jnp.float32) * scales_ref[:]


def _row_block(n: int, d: int, bytes_per_elt: int = 4) -> int:
    """Rows per grid step, sized to ~4 MB of VMEM per staged block so
    arbitrarily large matrices (e.g. a 30k x 768 embedding) compile —
    a single whole-array block caps out at VMEM (~16 MB)."""
    target = (4 * 1024 * 1024) // max(1, d * bytes_per_elt)
    block = max(8, min(n, target) // 8 * 8)
    return block


def quantize_rowwise(
    x,
    *,
    stochastic: bool | None = None,
    seed: int = 0,
    interpret: bool | None = None,
):
    """int8-quantize each row of a 2-D array with a per-row scale.

    ``stochastic`` defaults to True on TPU (hardware PRNG), False in
    interpret mode (the interpreter's PRNG is slow and tests want
    determinism).
    """
    if x.ndim != 2:
        raise ValueError(f"expected 2-D input, got shape {x.shape}")
    if interpret is None:
        interpret = _auto_interpret()
    if stochastic is None:
        stochastic = not interpret
    n, d = x.shape
    bn = _row_block(n, d)
    pad = (-n) % bn
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    seed_arr = jnp.asarray([seed], jnp.int32)
    values, scales = pl.pallas_call(
        functools.partial(_quantize_kernel, stochastic=stochastic),
        grid=((n + pad) // bn,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n + pad, d), jnp.int8),
            jax.ShapeDtypeStruct((n + pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, x)
    return (values[:n], scales[:n]) if pad else (values, scales)


def dequantize_rowwise(values, scales, *, interpret: bool | None = None):
    if interpret is None:
        interpret = _auto_interpret()
    n, d = values.shape
    # Block by the f32 OUTPUT element size — the output block is the
    # largest VMEM resident here, not the int8 input.
    bn = _row_block(n, d)
    pad = (-n) % bn
    if pad:
        values = jnp.pad(values, ((0, pad), (0, 0)))
        scales = jnp.pad(scales, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=((n + pad) // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n + pad, d), jnp.float32),
        interpret=interpret,
    )(values, scales)
    return out[:n] if pad else out


# -- quantized artifact format (pytree level) --------------------------------


class QuantizedLeaf:
    """Host-side container for one int8-quantized parameter tensor.

    The on-disk unit of the quantized artifact format: row-wise int8
    values + per-row f32 scales + the original shape/dtype.  Plain
    numpy fields, so dill/pickle round-trips it without this module
    imported at save time on the reader's side.
    """

    __slots__ = ("values", "scales", "shape", "dtype")

    def __init__(self, values, scales, shape, dtype):
        self.values = values
        self.scales = scales
        self.shape = tuple(shape)
        self.dtype = str(dtype)

    def __repr__(self):
        return (f"QuantizedLeaf(shape={self.shape}, dtype={self.dtype}, "
                f"int8+scales)")


# Below this many elements a tensor stays full precision: biases and
# norm scales are tiny (no footprint win) and precision-critical.
_QUANT_MIN_ELEMENTS = 4096


def quantize_pytree(tree, *, min_elements: int = _QUANT_MIN_ELEMENTS):
    """int8-quantize every large float tensor of a (host) pytree.

    >=2-D float leaves with at least ``min_elements`` elements become
    :class:`QuantizedLeaf` (leading axes flattened so the row-wise
    kernel sees 2-D); everything else passes through untouched.
    Rounding is DETERMINISTIC (round-to-nearest): a persistence format
    must load the same bytes every save — stochastic rounding is for
    in-training accumulation, not artifacts.
    """
    import numpy as np

    def leaf_fn(l):
        arr = np.asarray(l)
        if (
            arr.ndim >= 2
            and arr.size >= min_elements
            and np.issubdtype(arr.dtype, np.floating)
        ):
            mat = jnp.asarray(
                arr.astype(np.float32).reshape(-1, arr.shape[-1])
            )
            values, scales = quantize_rowwise(mat, stochastic=False)
            return QuantizedLeaf(
                np.asarray(values), np.asarray(scales),
                arr.shape, arr.dtype,
            )
        return l

    return jax.tree_util.tree_map(leaf_fn, tree)


def dequantize_pytree(tree):
    """Inverse of :func:`quantize_pytree`: QuantizedLeaf → dense array
    in the original shape/dtype; other leaves pass through."""
    import numpy as np

    def leaf_fn(l):
        if isinstance(l, QuantizedLeaf):
            mat = dequantize_rowwise(
                jnp.asarray(l.values), jnp.asarray(l.scales)
            )
            return np.asarray(mat).reshape(l.shape).astype(l.dtype)
        return l

    return jax.tree_util.tree_map(
        leaf_fn, tree,
        is_leaf=lambda x: isinstance(x, QuantizedLeaf),
    )


def has_quantized_leaves(tree) -> bool:
    return any(
        isinstance(l, QuantizedLeaf)
        for l in jax.tree_util.tree_leaves(
            tree, is_leaf=lambda x: isinstance(x, QuantizedLeaf)
        )
    )
