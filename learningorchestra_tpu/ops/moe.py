"""Mixture-of-experts feed-forward layer (expert parallelism).

Beyond-parity headroom: the reference has no conditional-compute story
at all (its model zoo is dense keras/sklearn — SURVEY §2.3); this adds
a GShard/Switch-style MoE FFN designed for the ``ep`` mesh axis
(parallel/mesh.py).

TPU-first design decisions:

- **Static shapes everywhere.**  Routing uses a fixed per-expert
  capacity ``C`` computed from static shapes, so the dispatched tensor
  is always ``(experts, batch, C, hidden)`` — no dynamic gather sizes,
  no recompiles, and XLA can tile every einsum onto the MXU.  Tokens
  over capacity are dropped (their combine weight is zero and the
  residual connection carries them through unchanged — the standard
  Switch trade).
- **Dispatch/combine as einsums, not gathers.**  The one-hot dispatch
  tensor turns routing into two batched matmuls; with expert weights
  sharded ``P('ep', ...)`` XLA's SPMD partitioner lowers the expert
  dimension contraction to an all_to_all over ``ep`` — the collective
  rides ICI, never the host.
- **Router in f32.**  Gating softmax/argmax run in float32 regardless
  of the compute dtype (bf16 router logits measurably destabilise
  top-k choices at scale); expert matmuls run in the model dtype.

The load-balancing auxiliary loss is sown into the ``'losses'``
collection; ``train/neural.py`` adds every sown value to the training
objective (dense models sow nothing and pay nothing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class MoEMlp(nn.Module):
    """Top-k routed expert FFN: drop-in for a transformer's dense MLP.

    Output shape equals input shape ``(batch, seq, hidden)``.  With
    ``num_experts=1`` this degenerates to a plain (gelu) FFN whose
    combine weight is exactly 1 for every token — the equivalence test
    in tests/test_moe.py pins that.
    """

    num_experts: int
    hidden_dim: int
    mlp_dim: int
    top_k: int = 2
    capacity_factor: float = 1.5
    aux_loss_weight: float = 1e-2
    router_z_weight: float = 1e-3
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)

    @nn.compact
    def __call__(self, x):
        b, t, h = x.shape
        e = self.num_experts
        k = min(self.top_k, e)
        # Per-expert slots per GROUP (= batch row): every token admitted
        # if routing were perfectly balanced, times headroom.
        cap = max(1, -(-(k * t * self.capacity_factor) // e).__int__())
        cap = min(cap, t * k)

        # -- routing (f32) --------------------------------------------
        logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(x.astype(jnp.float32))  # (B, T, E)
        probs = jax.nn.softmax(logits, axis=-1)

        remaining = probs
        assigned = jnp.zeros((b, e), jnp.float32)  # slots used so far
        slot_oh, slot_gate, slot_pos = [], [], []
        for _ in range(k):
            idx = jnp.argmax(remaining, axis=-1)  # (B, T)
            oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (B, T, E)
            slot_gate.append((remaining * oh).sum(-1))  # (B, T)
            remaining = remaining * (1.0 - oh)
            # Position of each token inside its expert's capacity
            # buffer: tokens earlier in the sequence fill lower slots;
            # later routing slots stack after earlier ones.
            pos = jnp.cumsum(oh, axis=1) - oh + assigned[:, None, :]
            slot_pos.append((pos * oh).sum(-1).astype(jnp.int32))  # (B, T)
            slot_oh.append(oh)
            assigned = assigned + oh.sum(axis=1)

        # Renormalise the selected gates to sum to 1 per token BEFORE
        # capacity drops (GShard: drops lose mass rather than re-weight
        # the survivors).
        denom = sum(slot_gate) + 1e-9
        dispatch = jnp.zeros((b, t, e, cap), jnp.float32)
        combine = jnp.zeros((b, t, e, cap), jnp.float32)
        for oh, gate, pos in zip(slot_oh, slot_gate, slot_pos):
            keep = (pos < cap).astype(jnp.float32)  # (B, T)
            pos_oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32)
            sel = oh[..., None] * pos_oh[:, :, None, :] * keep[..., None, None]
            dispatch = dispatch + sel
            combine = combine + (gate / denom)[..., None, None] * sel

        # -- load-balancing aux loss (Switch eq. 4, over 1st choices) --
        if not self.is_initializing():
            frac = slot_oh[0].mean(axis=(0, 1))  # dispatch fraction / e
            prob = probs.mean(axis=(0, 1))  # mean router prob / e
            aux = e * jnp.sum(frac * prob) * self.aux_loss_weight
            z = jnp.mean(
                jax.scipy.special.logsumexp(logits, axis=-1) ** 2
            ) * self.router_z_weight
            self.sow("losses", "moe_aux", aux + z)

        # -- expert compute (model dtype) ------------------------------
        w1 = self.param(
            "expert_w1",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, h, self.mlp_dim),
        )
        b1 = self.param(
            "expert_b1", nn.initializers.zeros, (e, self.mlp_dim)
        )
        w2 = self.param(
            "expert_w2",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, self.mlp_dim, h),
        )
        b2 = self.param("expert_b2", nn.initializers.zeros, (e, h))

        dt = self.dtype if self.dtype is not None else x.dtype
        xe = jnp.einsum(
            "btec,bth->ebch", dispatch.astype(dt), x.astype(dt)
        )  # (E, B, C, H)
        h1 = jnp.einsum("ebch,ehm->ebcm", xe, w1.astype(dt))
        h1 = nn.gelu(h1 + b1.astype(dt)[:, None, None, :])
        h2 = jnp.einsum("ebcm,emh->ebch", h1, w2.astype(dt))
        h2 = h2 + b2.astype(dt)[:, None, None, :]
        return jnp.einsum("btec,ebch->bth", combine.astype(dt), h2)
