"""TPU kernel library (Pallas).

Hot ops implemented as Pallas TPU kernels with jnp reference
implementations for CPU and for numerical testing.  The reference system
has no first-party kernels (its numerics live in wrapped toolkits,
SURVEY §2.3); this package is the TPU-native replacement for that layer's
hot path — attention is the dominant op of the flagship BERT workload
(BASELINE.md config 4).
"""

from learningorchestra_tpu.ops.attention import (
    flash_attention,
    mha_reference,
)
from learningorchestra_tpu.ops.layers import MultiHeadSelfAttention

__all__ = ["flash_attention", "mha_reference", "MultiHeadSelfAttention"]
