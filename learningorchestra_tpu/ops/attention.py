"""Flash attention — blockwise online-softmax attention as a Pallas TPU
kernel with a custom VJP (forward and backward both Pallas).

The reference system's attention lives inside wrapped keras models and is
materialised as a full (T, T) score matrix per head; this kernel never
materialises scores — it streams K/V blocks through VMEM with the online
softmax (running max / running sum) recurrence, so HBM traffic is O(T·D)
instead of O(T²) and the MXU sees (block_q × D) @ (D × block_k) matmuls.

Numerical contract (tested against ``mha_reference``):
- matmuls multiply in the storage dtype (bf16 on the training path —
  full MXU rate) and ACCUMULATE in float32 via
  ``preferred_element_type``; the softmax/online-max recurrence runs in
  float32, with the probabilities/dS downcast to the storage dtype for
  the second matmul of each pass (standard flash-attention precision);
- key-side padding mask: masked keys contribute zero probability; rows
  whose keys are ALL masked output exactly 0 (and get zero gradient).

On non-TPU backends the same kernels run in Pallas interpret mode, which
is how the unit tests exercise them on CPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30  # additive mask value; exp(_NEG_BIG - lse) == 0 in f32
_LSE_EMPTY = 1e30  # lse sentinel for fully-masked rows: exp(s - 1e30) == 0

# jax renamed TPUCompilerParams → CompilerParams across releases.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _tpu_params(n_parallel: int):
    """Mark the trailing grid axis sequential (carry in VMEM scratch)
    and the leading ones parallel, so Mosaic pipelines the K/V block
    DMAs against compute (double buffering)."""
    return _CompilerParams(
        dimension_semantics=("parallel",) * n_parallel + ("arbitrary",)
    )


def _auto_interpret() -> bool:
    # LO_TPU_FLASH_INTERPRET overrides the backend heuristic: "0"
    # forces the real Mosaic lowering on a CPU-only host — used by the
    # cross-platform export test that proves the TRAIN path lowers to
    # tpu_custom_call without needing live TPU hardware.
    env = os.environ.get("LO_TPU_FLASH_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "False")
    return jax.default_backend() != "tpu"


def _validate_window(window, causal) -> None:
    if window is None:
        return
    if not causal:
        raise ValueError("window requires causal=True")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


# ---------------------------------------------------------------------------
# Reference implementation (jnp) — ground truth for tests and CPU fallback.
# ---------------------------------------------------------------------------


def mha_reference(q, k, v, key_mask=None, causal: bool = False,
                  window: int | None = None):
    """Plain multi-head attention. q,k,v: (B, H, T, D); key_mask: (B, Tk).

    Fully-masked rows output exactly 0 with exactly-0 gradients.  The
    masking uses the double-``where`` pattern: masked lanes never touch a
    live value on either the forward or backward path (a single ``where``
    after ``exp`` leaves NaN-producing -1e30 arithmetic on the grad path).
    ``causal=True`` additionally masks keys beyond each query's position
    (decoder self-attention; Tq must equal Tk); ``window`` restricts each
    query to its last ``window`` positions (sliding-window attention).
    """
    _validate_window(window, causal)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum(
        "bhqd,bhkd->bhqk",
        q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    tq, tk = q.shape[2], k.shape[2]
    maskb = None
    if key_mask is not None:
        maskb = key_mask.astype(bool)[:, None, None, :]
    if causal:
        rows = jnp.arange(tq)[:, None]
        cols = jnp.arange(tk)[None, :]
        tri = cols <= rows
        if window is not None:
            tri = tri & (cols > rows - window)
        maskb = tri[None, None] if maskb is None else (
            maskb & tri[None, None]
        )
    if maskb is None:
        p = jax.nn.softmax(s, axis=-1)
    else:
        m = jnp.max(jnp.where(maskb, s, _NEG_BIG), axis=-1, keepdims=True)
        # Fully-masked rows: make the subtraction a no-op so the masked
        # branch below sees a clean constant, not (-1e30) - (-1e30).
        m = jnp.where(m > _NEG_BIG / 2, m, 0.0)
        p = jnp.exp(jnp.where(maskb, s - m, _NEG_BIG))  # exp(-1e30) == 0
        denom = jnp.sum(p, axis=-1, keepdims=True)
        p = p / jnp.maximum(denom, 1e-30)  # all-masked rows: 0/1e-30 == 0
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
    ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _causal_keep(i, j, bq, bk, window=None):
    """(bq, bk) multiplicative mask for the causal region of block
    (i, j): 1.0 where global col <= global row — and, with a sliding
    ``window``, col > row - window (each query sees its last ``window``
    positions only, Mistral-style banded attention)."""
    rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = cols <= rows
    if window is not None:
        keep = keep & (cols > rows - window)
    return keep.astype(jnp.float32)


def _block_live(i, j, bq, bk, causal, window):
    """Predicate builder: should block (i, j) compute at all?  Causal
    kills blocks fully above the diagonal; a window additionally kills
    blocks fully left of the band."""
    live = True
    if causal:
        live = j * bk < (i + 1) * bq
    if window is not None:
        live = live & ((j + 1) * bk + window - 1 > i * bq)
    return live


def _win_lo(i, bq, bk, window):
    """First k-block that can intersect q-block ``i``'s band."""
    return jnp.maximum(0, (i * bq - (window - 1)) // bk)


def _win_k_slots(bq, bk, window, nk):
    """Grid length of the streamed k axis under a window: the band of
    one q block spans bq + window - 1 columns -> a CONSTANT number of
    k blocks, so HBM traffic is O(T·window), not O(T²).  (Without
    this, pl.when would skip the MXU work but the BlockSpec pipeline
    would still DMA every K/V block.)"""
    return min(nk, (bq + window - 1 + bk - 1) // bk + 1)


def _fwd_kernel(
    q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, window,
):
    """One (q-block, k-block) grid step.  The k axis is the innermost,
    sequential grid dimension: the online-softmax running state lives in
    VMEM scratch across k steps, and each step sees ONE (bk, D) K/V block
    streamed from HBM — VMEM use is O(block), not O(T), and Mosaic
    overlaps the next block's DMA with this block's MXU work.  Causal
    blocks fully above the diagonal skip their compute entirely."""
    i = pl.program_id(2)
    jj = pl.program_id(3)
    nk = pl.num_programs(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    # Windowed grids stream only the band's k blocks; jj is an offset
    # from the band's first block, not an absolute block index.
    j = jj if window is None else _win_lo(i, bq, bk, window) + jj

    @pl.when(jj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        # Matmul inputs stay in their storage dtype (bf16 on the
        # training path): the MXU multiplies bf16 at full rate and
        # accumulates f32 via preferred_element_type — upcasting first
        # would halve throughput.
        q = q_ref[0, 0]  # (bq, D)
        kb = k_ref[0, 0]  # (bk, D)
        vb = v_ref[0, 0]
        keep = km_ref[0]  # (1, bk) float32, 1=keep
        if causal:
            keep = keep * _causal_keep(i, j, bq, bk, window)  # (bq, bk)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (bq, bk) f32
        s = s + (keep - 1.0) * -_NEG_BIG  # masked keys -> -1e30
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new) * keep  # zero masked keys exactly
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(
            p, axis=-1, keepdims=True
        )
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_block_live(i, j, bq, bk, causal, window))(_compute)
    else:
        _compute()

    @pl.when(jj == nk - 1)
    def _finalize():
        l = l_scr[...]
        nonempty = l > 0.0
        out = jnp.where(
            nonempty, acc_scr[...] / jnp.where(nonempty, l, 1.0), 0.0
        )
        o_ref[0, 0] = out.astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            nonempty,
            m_scr[...] + jnp.log(jnp.maximum(l, 1e-30)),
            _LSE_EMPTY,
        )


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, km_ref, do_ref, lse_ref, delta_ref, dq_ref,
    dq_scr, *, scale, causal, window,
):
    """dQ pass: grid (b, h, nq, nk) — same streamed K/V layout as the
    forward; dq accumulates in VMEM scratch across the sequential k axis."""
    i = pl.program_id(2)
    jj = pl.program_id(3)
    nk = pl.num_programs(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    j = jj if window is None else _win_lo(i, bq, bk, window) + jj

    @pl.when(jj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # (bq, 1)
        delta = delta_ref[0, 0]
        kb = k_ref[0, 0]
        vb = v_ref[0, 0]
        keep = km_ref[0]
        if causal:
            keep = keep * _causal_keep(i, j, bq, bk, window)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s + (keep - 1.0) * -_NEG_BIG
        p = jnp.exp(s - lse) * keep  # (bq, bk) f32
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * scale).astype(kb.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_block_live(i, j, bq, bk, causal, window))(_compute)
    else:
        _compute()

    @pl.when(jj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, km_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, window, nq_total,
):
    """dK/dV pass: grid (b, h, nk, nq) — one K/V block is resident while
    Q/dO/lse/delta blocks stream along the sequential inner q axis."""
    j = pl.program_id(2)
    ii = pl.program_id(3)
    nq = pl.num_programs(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    # Windowed grids stream only the band's q blocks for this k block.
    i = ii if window is None else (j * bk) // bq + ii

    @pl.when(ii == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        kb = k_ref[0, 0]  # (bk, D)
        vb = v_ref[0, 0]
        keep = km_ref[0]  # (1, bk)
        if causal:
            keep = keep * _causal_keep(i, j, bq, bk, window)
        q = q_ref[0, 0]  # (bq, D)
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # (bq, 1)
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s + (keep - 1.0) * -_NEG_BIG
        p = jnp.exp(s - lse) * keep  # (bq, bk) f32
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live = _block_live(i, j, bq, bk, causal, window)
    if window is not None:
        live = live & (i < nq_total)
    if causal:
        pl.when(live)(_compute)
    else:
        _compute()

    @pl.when(ii == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _k_index_maps(block_q, block_k, window, nk):
    """(4-D K/V map, 3-D mask map) for the streamed k axis.  Windowed
    grids translate the per-band offset jj to an absolute block index,
    clipped into range — the clipped duplicates at the edges are DMA'd
    but skipped by the kernel's live predicate."""
    if window is None:
        return (lambda bb, hh, i, j: (bb, hh, j, 0)), (
            lambda bb, hh, i, j: (bb, 0, j))

    def kv(bb, hh, i, jj):
        j = _win_lo(i, block_q, block_k, window) + jj
        return (bb, hh, jnp.clip(j, 0, nk - 1), 0)

    def mask(bb, hh, i, jj):
        j = _win_lo(i, block_q, block_k, window) + jj
        return (bb, 0, jnp.clip(j, 0, nk - 1))

    return kv, mask


def _fwd_call(q, k, v, km, block_q, block_k, interpret, causal,
              window=None):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    nq, nk = tq // block_q, tk // block_k
    nk_grid = nk if window is None else _win_k_slots(
        block_q, block_k, window, nk
    )
    kv_map, mask_map = _k_index_maps(block_q, block_k, window, nk)
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window
    )
    return pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk_grid),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bb, hh, i, j: (bb, hh, i, 0)
            ),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, 1, block_k), mask_map),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bb, hh, i, j: (bb, hh, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda bb, hh, i, j: (bb, hh, i, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_tpu_params(3),
        interpret=interpret,
    )(q, k, v, km)


def _bwd_call(q, k, v, km, do, lse, delta, block_q, block_k, interpret,
              causal, window=None):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    nq, nk = tq // block_q, tk // block_k
    nk_grid = nk if window is None else _win_k_slots(
        block_q, block_k, window, nk
    )
    kv_map, mask_map = _k_index_maps(block_q, block_k, window, nk)
    scale = 1.0 / (d ** 0.5)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, window=window
        ),
        grid=(b, h, nq, nk_grid),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bb, hh, i, j: (bb, hh, i, 0)
            ),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, 1, block_k), mask_map),
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bb, hh, i, j: (bb, hh, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda bb, hh, i, j: (bb, hh, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda bb, hh, i, j: (bb, hh, i, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bb, hh, i, j: (bb, hh, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_tpu_params(3),
        interpret=interpret,
    )(q, k, v, km, do, lse, delta)

    if window is None:
        nq_grid = nq
        q_map = lambda bb, hh, j, i: (bb, hh, i, 0)  # noqa: E731
    else:
        # One k block's band spans bk + window - 1 rows of q.
        nq_grid = min(nq, (block_k + window - 1 + block_q - 1)
                      // block_q + 1)

        def q_map(bb, hh, j, ii):
            i = (j * block_k) // block_q + ii
            return (bb, hh, jnp.clip(i, 0, nq - 1), 0)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, window=window,
            nq_total=nq,
        ),
        grid=(b, h, nk, nq_grid),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda bb, hh, j, i: (bb, hh, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda bb, hh, j, i: (bb, hh, j, 0)
            ),
            pl.BlockSpec((1, 1, block_k), lambda bb, hh, j, i: (bb, 0, j)),
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_q, 1), q_map),
            pl.BlockSpec((1, 1, block_q, 1), q_map),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_k, d), lambda bb, hh, j, i: (bb, hh, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda bb, hh, j, i: (bb, hh, j, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_tpu_params(3),
        interpret=interpret,
    )(q, k, v, km, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp core (operates on block-aligned shapes)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, km, block_q, block_k, interpret, causal,
                window):
    o, _ = _fwd_call(
        q, k, v, km, block_q, block_k, interpret, causal, window
    )
    return o


def _flash_core_fwd(q, k, v, km, block_q, block_k, interpret, causal,
                    window):
    o, lse = _fwd_call(
        q, k, v, km, block_q, block_k, interpret, causal, window
    )
    return o, (q, k, v, km, o, lse)


def _flash_core_bwd(block_q, block_k, interpret, causal, window, res, g):
    q, k, v, km, o, lse = res
    do = g.astype(jnp.float32)
    # (B, H, Tq, 1) — trailing singleton keeps TPU block shapes legal.
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1, keepdims=True)
    dq, dk, dv = _bwd_call(
        q, k, v, km, do.astype(q.dtype), lse, delta,
        block_q, block_k, interpret, causal, window,
    )
    return dq, dk, dv, jnp.zeros_like(km)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


def flash_attention(
    q,
    k,
    v,
    key_mask=None,
    *,
    causal: bool = False,
    window: int | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Blockwise attention. q,k,v: (B, H, T, D); key_mask: (B, Tk) bool.

    Sequences are padded to block multiples internally; padded keys are
    masked out, padded query rows are sliced off the output.

    Default blocks follow the TPU v5e sweep (TPU_EVIDENCE.md): (256,
    512) wins for T <= 8k (1.20x XLA), (512, 1024) for longer (2.80x at
    T=32k, where XLA OOMs with masks); 128-sized blocks leave the MXU
    idle on grid overhead (~4 MFLOP per step).
    """
    if interpret is None:
        interpret = _auto_interpret()
    _validate_window(window, causal)
    t_longest = max(q.shape[2], k.shape[2])
    if block_q is None:
        block_q = 256 if t_longest <= 8192 else 512
    if block_k is None:
        block_k = 512 if t_longest <= 8192 else 1024
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = min(block_q, max(8, tq))
    block_k = min(block_k, max(8, tk))
    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k

    if key_mask is None:
        key_mask = jnp.ones((b, tk), jnp.float32)
    km = key_mask.astype(jnp.float32)[:, None, :]  # (B, 1, Tk)
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        km = jnp.pad(km, ((0, 0), (0, 0), (0, pad_k)))

    out = _flash_core(
        q, k, v, km, block_q, block_k, interpret, causal, window
    )
    if pad_q:
        out = out[:, :, :tq]
    return out
