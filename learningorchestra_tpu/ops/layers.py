"""Flax layers backed by the Pallas kernel library.

``MultiHeadSelfAttention`` is the transformer models' attention layer:
QKV/output projections as feature-dim matmuls (shardable on a ``tp``
mesh axis) around the flash-attention kernel.  Off-TPU it dispatches to
the jnp reference instead of interpret mode — interpret-mode Pallas is
orders of magnitude slower and only meant for kernel tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from learningorchestra_tpu.ops.attention import (
    flash_attention,
    mha_reference,
)


def remat_block(cls, remat):
    """Wrap a block module class per the family-wide ``remat`` knob.

    ``False`` — no remat.  ``True`` — full recompute (O(layers) less
    activation HBM for ~1 extra forward of FLOPs).  ``"dots"`` —
    ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``: MXU
    outputs (matmuls/convs) stay resident, only the cheap elementwise
    work recomputes — usually the better FLOPs/HBM trade on TPU when
    memory allows (the MFU-sweep knob; VERDICT r3 item 2).
    """
    if not remat:
        return cls
    policy = None
    if remat == "dots":
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif remat is not True:
        raise ValueError(f"remat must be False|True|'dots', got {remat!r}")
    return nn.remat(cls, policy=policy)


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding on (B, H, T, hd) with positions (T,)
    or (B, T).  Rotates feature pairs (x[..., :hd/2], x[..., hd/2:])
    by position-scaled frequencies — attention scores then depend only
    on RELATIVE distance, so trained models extrapolate past max_len
    and need no learned position table."""
    hd = x.shape[-1]
    if hd % 2:
        raise ValueError(f"rope needs an even head_dim, got {hd}")
    half = hd // 2
    freqs = theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )  # (half,)
    pos = jnp.asarray(positions, jnp.float32)
    angles = pos[..., None] * freqs  # (T, half) or (B, T, half)
    if angles.ndim == 2:  # (T, half): shared across batch and heads
        angles = angles[None, None]
    elif angles.ndim == 3:  # (B, T, half): insert the head axis
        angles = angles[:, None]
    else:
        raise ValueError(f"positions must be (T,) or (B, T)")
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


def _grouped_decode_attend(q, k, v, key_mask):
    """Single-position attention against a (possibly grouped) KV cache.

    q: (B, H, 1, hd); k/v: (B, H_kv, Tk, hd) with H_kv | H.  Queries
    attend their group's KV head DIRECTLY — no jnp.repeat widening of
    the cache, so per-step HBM traffic stays at H_kv (the point of
    GQA).  key_mask (B, Tk) always marks at least the current position.
    """
    b, h, _, hd = q.shape
    kv_heads, tk = k.shape[1], k.shape[2]
    gsz = h // kv_heads
    qg = q.reshape(b, kv_heads, gsz, hd)
    s = jnp.einsum(
        "bhgd,bhkd->bhgk",
        qg.astype(jnp.float32), k.astype(jnp.float32),
    ) * (1.0 / hd ** 0.5)  # (B, H_kv, G, Tk)
    if key_mask is None:
        p = jax.nn.softmax(s, axis=-1)
    else:
        # Same double-where contract as mha_reference: fully-masked
        # rows (left-padded prompts at step 0) output exactly 0, not
        # the mean of the cache buffer.
        maskb = key_mask.astype(bool)[:, None, None, :]
        m = jnp.max(jnp.where(maskb, s, -1e30), axis=-1, keepdims=True)
        m = jnp.where(m > -5e29, m, 0.0)
        p = jnp.exp(jnp.where(maskb, s - m, -1e30))
        p = p / jnp.maximum(
            jnp.sum(p, axis=-1, keepdims=True), 1e-30
        )
    out = jnp.einsum("bhgk,bhkd->bhgd", p, v.astype(jnp.float32))
    return out.reshape(b, h, 1, hd).astype(q.dtype)


class MultiHeadSelfAttention(nn.Module):
    """Self-attention with a key-side padding mask (B, T).

    ``use_flash``: None → Pallas kernel on TPU, reference elsewhere;
    True/False forces a path (tests force both and compare).

    Default-on is hardware-validated: the streamed-K/V kernel compiles
    on TPU v5e, matches ``mha_reference`` to bf16 tolerance fwd+bwd
    across shapes (T 16..128k, D 8..128, padded/masked), and beats
    XLA's fused attention at long T (1.7x fwd / 3.5x bwd at T=16k;
    the reference OOMs beyond ~32k where the kernel keeps running).
    """

    num_heads: int
    qkv_features: int
    # Grouped-query attention: project K/V to ``num_kv_heads`` heads
    # (None = num_heads, plain MHA; 1 = multi-query).  Shrinks the
    # decode KV cache and K/V projection FLOPs by H/H_kv; each KV head
    # serves a contiguous group of query heads.
    num_kv_heads: int | None = None
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)
    use_flash: bool | None = None
    causal: bool = False
    # Sliding-window (banded causal) attention: each query sees its
    # last ``window`` positions.  O(T*window) cost on the flash path —
    # off-diagonal blocks outside the band skip compute entirely.
    window: int | None = None
    # Rotary position embeddings applied to q/k (the model skips its
    # learned position table when this is on).
    rope: bool = False
    # Autoregressive inference: cache K/V per position in a 'cache'
    # variable collection (apply with mutable=['cache']).  Initialize
    # by running the module on a FULL-length input (flax convention:
    # the uninitialized pass behaves as a normal forward and sizes the
    # cache); then feed one position at a time.
    decode: bool = False
    # One (H + 2·H_kv, hd) projection instead of three — the MXU wants
    # fewer, LARGER matmuls: at short sequence lengths the three small
    # per-layer projections are dispatch/tiling-bound, and XLA does not
    # merge separate dots on its own.  Same math (the fused weight is
    # the block-stack of the three), same init variance (fan_in is the
    # model dim either way).  Trade: under tp>1 the fused head axis
    # cannot cleanly head-shard (parallel/sharding.py replicates it) —
    # Megatron-style tensor-parallel attention should set
    # fused_qkv=False.  Legacy separate-projection artifacts load via
    # ops.layers.migrate_separate_qkv (applied automatically on the
    # estimator load paths).
    fused_qkv: bool = True

    @nn.compact
    def __call__(self, x, key_mask=None):
        b, t, _ = x.shape
        head_dim = self.qkv_features // self.num_heads
        if head_dim * self.num_heads != self.qkv_features:
            raise ValueError("qkv_features must be divisible by num_heads")
        kv_heads = self.num_heads if self.num_kv_heads is None \
            else self.num_kv_heads
        if kv_heads < 1:
            raise ValueError(f"num_kv_heads must be >= 1, got {kv_heads}")
        if self.num_heads % kv_heads:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by "
                f"num_kv_heads={kv_heads}"
            )

        if self.fused_qkv:
            qkv = nn.DenseGeneral(
                (self.num_heads + 2 * kv_heads, head_dim),
                dtype=self.dtype, name="qkv",
            )(x).transpose(0, 2, 1, 3)  # (B, H+2H_kv, T, hd)
            q = qkv[:, : self.num_heads]
            k = qkv[:, self.num_heads: self.num_heads + kv_heads]
            v = qkv[:, self.num_heads + kv_heads:]
        else:
            def proj(name, heads):
                y = nn.DenseGeneral(
                    (heads, head_dim), dtype=self.dtype, name=name
                )(x)
                return y.transpose(0, 2, 1, 3)  # (B, heads, T, hd)

            q = proj("query", self.num_heads)
            k = proj("key", kv_heads)
            v = proj("value", kv_heads)
        is_initialized = self.decode and self.has_variable(
            "cache", "cached_key"
        )
        if self.rope and not is_initialized:
            pos = jnp.arange(t)
            q = apply_rope(q, pos)
            k = apply_rope(k, pos)

        def widen(kv):
            # Broadcast each KV head to its query-head group.  The
            # repeat happens AFTER caching, so the cache (and its HBM
            # traffic) stays at kv_heads.
            if kv_heads == self.num_heads:
                return kv
            return jnp.repeat(kv, self.num_heads // kv_heads, axis=1)

        if self.decode:
            # Flax decode convention: the variables are declared once;
            # an uninitialized pass (module.init / eval_shape on the
            # FULL-length input) merely sizes them and falls through to
            # the normal forward below.
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               k.shape, k.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               v.shape, v.dtype)
            ci = self.variable(
                "cache", "cache_index",
                lambda: jnp.zeros((), jnp.int32),
            )
            if is_initialized:
                # A (B,)-shaped cache_index means each batch row sits
                # at its OWN position — the continuous-batching engine
                # steps a mixed pool of sequences with one executable.
                # A scalar index keeps the classic lockstep semantics.
                idx = ci.value
                batched_idx = idx.ndim == 1
                if self.rope:
                    # Rotate at the CURRENT position before caching —
                    # the cache holds rotated keys, so lookups need no
                    # re-rotation.
                    pos1 = idx[:, None] if batched_idx \
                        else jnp.full((1,), idx)
                    q = apply_rope(q, pos1)
                    k = apply_rope(k, pos1)
                if t != 1:
                    # Multi-token chunks would need an intra-chunk
                    # causal mask (the per-batch key_mask has no
                    # per-query component) — without one, position 0 of
                    # the chunk would attend to positions 1..t-1.
                    raise ValueError(
                        "decode mode feeds ONE position per step; got "
                        f"a {t}-token chunk (prefill runs through the "
                        "scan one token at a time)"
                    )
                tk_cache = ck.value.shape[2]
                if batched_idx:
                    # Per-row one-hot select writes: row r lands at
                    # slot idx[r].  jnp.where is bit-exact against
                    # dynamic_update_slice for the written lane and
                    # leaves every other lane untouched.
                    hot = jnp.arange(tk_cache)[None, :] == idx[:, None]
                    sel = hot[:, None, :, None]
                    ck.value = jnp.where(sel, k, ck.value)
                    cv.value = jnp.where(sel, v, cv.value)
                else:
                    ck.value = jax.lax.dynamic_update_slice(
                        ck.value, k, (0, 0, idx, 0)
                    )
                    cv.value = jax.lax.dynamic_update_slice(
                        cv.value, v, (0, 0, idx, 0)
                    )
                ci.value = idx + t
                # Causality is enforced HERE — the layer owns
                # cache_index, so it ANDs a validity mask (slots beyond
                # the just-written position are zero-initialized cache,
                # not real keys) into whatever key_mask the caller
                # passed, including none at all.  Flash brings nothing
                # for T_q == 1 queries.  The sliding window is likewise
                # the layer's invariant, not each decode loop's.
                slot = jnp.arange(tk_cache)[None, :]
                bound = idx[:, None] if batched_idx else idx
                valid = slot <= bound
                if self.window is not None:
                    valid = valid & (slot > (bound - self.window))
                key_mask = valid if key_mask is None else (
                    key_mask & valid
                )
                out = _grouped_decode_attend(
                    q, ck.value, cv.value, key_mask
                )
                out = out.transpose(0, 2, 1, 3).reshape(
                    b, t, self.qkv_features
                )
                return nn.DenseGeneral(
                    self.qkv_features, dtype=self.dtype, name="out"
                )(out)

        use_flash = self.use_flash
        if use_flash is None:
            use_flash = jax.default_backend() == "tpu"
        attend = flash_attention if use_flash else mha_reference
        out = attend(
            q, widen(k), widen(v), key_mask,
            causal=self.causal, window=self.window,
        )  # (B,H,T,hd)
        out = out.transpose(0, 2, 1, 3).reshape(b, t, self.qkv_features)
        return nn.DenseGeneral(
            self.qkv_features, dtype=self.dtype, name="out"
        )(out)


def migrate_separate_qkv(tree):
    """Convert a legacy separate-projection parameter tree
    (query/key/value DenseGeneral triplets) to the fused ``qkv``
    layout — the exact block-stack the fused layer computes, so
    outputs are bit-identical.  Non-matching subtrees pass through;
    the estimator load paths apply this automatically when they see
    the legacy pattern."""
    import numpy as np

    def _is_proj(node):
        return isinstance(node, dict) and "kernel" in node

    def walk(node):
        if not isinstance(node, dict):
            return node
        if (
            {"query", "key", "value"} <= set(node)
            and all(_is_proj(node[k]) for k in ("query", "key", "value"))
        ):
            node = dict(node)
            q = node.pop("query")
            k = node.pop("key")
            v = node.pop("value")
            node["qkv"] = {
                "kernel": np.concatenate(
                    [np.asarray(q["kernel"]), np.asarray(k["kernel"]),
                     np.asarray(v["kernel"])], axis=1,
                ),
                "bias": np.concatenate(
                    [np.asarray(q["bias"]), np.asarray(k["bias"]),
                     np.asarray(v["bias"])], axis=0,
                ),
            }
        return {kk: walk(vv) for kk, vv in node.items()}

    return walk(tree)


def has_separate_qkv(tree) -> bool:
    """True when the tree holds legacy query/key/value triplets."""
    found = {"hit": False}

    def walk(node):
        if not isinstance(node, dict) or found["hit"]:
            return
        if {"query", "key", "value"} <= set(node):
            found["hit"] = True
            return
        for v in node.values():
            walk(v)

    walk(tree)
    return found["hit"]
