"""Flax layers backed by the Pallas kernel library.

``MultiHeadSelfAttention`` is the transformer models' attention layer:
QKV/output projections as feature-dim matmuls (shardable on a ``tp``
mesh axis) around the flash-attention kernel.  Off-TPU it dispatches to
the jnp reference instead of interpret mode — interpret-mode Pallas is
orders of magnitude slower and only meant for kernel tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from learningorchestra_tpu.ops.attention import (
    flash_attention,
    mha_reference,
)


class MultiHeadSelfAttention(nn.Module):
    """Self-attention with a key-side padding mask (B, T).

    ``use_flash``: None → Pallas kernel on TPU, reference elsewhere;
    True/False forces a path (tests force both and compare).

    Default-on is hardware-validated: the streamed-K/V kernel compiles
    on TPU v5e, matches ``mha_reference`` to bf16 tolerance fwd+bwd
    across shapes (T 16..128k, D 8..128, padded/masked), and beats
    XLA's fused attention at long T (1.7x fwd / 3.5x bwd at T=16k;
    the reference OOMs beyond ~32k where the kernel keeps running).
    """

    num_heads: int
    qkv_features: int
    dtype: jnp.dtype = jnp.float32
    use_flash: bool | None = None
    causal: bool = False

    @nn.compact
    def __call__(self, x, key_mask=None):
        b, t, _ = x.shape
        head_dim = self.qkv_features // self.num_heads
        if head_dim * self.num_heads != self.qkv_features:
            raise ValueError("qkv_features must be divisible by num_heads")

        def proj(name):
            y = nn.DenseGeneral(
                (self.num_heads, head_dim), dtype=self.dtype, name=name
            )(x)
            return y.transpose(0, 2, 1, 3)  # (B, H, T, hd)

        q, k, v = proj("query"), proj("key"), proj("value")
        use_flash = self.use_flash
        if use_flash is None:
            use_flash = jax.default_backend() == "tpu"
        attend = flash_attention if use_flash else mha_reference
        out = attend(q, k, v, key_mask, causal=self.causal)  # (B,H,T,hd)
        out = out.transpose(0, 2, 1, 3).reshape(b, t, self.qkv_features)
        return nn.DenseGeneral(
            self.qkv_features, dtype=self.dtype, name="out"
        )(out)
