"""Partition rules: model pytrees and data batches onto the mesh.

The reference replicates the whole model to every worker by JSON round-trip
(reference: microservices/binary_executor_image/binary_execution.py:248-251
``to_json``/``model_from_json``) and ships weights as Python lists.  Here
placement is a `NamedSharding` per leaf, computed once from shapes; XLA
moves bytes over ICI, and the "replicate vs shard" decision is a rule, not
a serialization format.

Heuristics (correctness never depends on them — shardings are placement
constraints; XLA's SPMD partitioner inserts whatever collectives the
annotated program needs):

- 2-D kernels ``(in, out)``: out-features over ``tp``, in-features over
  ``fsdp`` — the Megatron column-parallel default for the MLP hot path;
- embeddings ``(vocab, hidden)``: vocab over ``tp`` (row-parallel lookup);
- conv kernels ``(h, w, cin, cout)``: cout over ``tp``;
- 1-D (bias/scale) and anything non-divisible: replicated;
- batches: leading axis over ``(dp, fsdp)`` — fsdp is a data axis too.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _divisible(dim: int, n: int) -> bool:
    return n > 1 and dim % n == 0


def leaf_spec(path: tuple, shape: tuple, mesh: Mesh) -> P:
    """PartitionSpec for one parameter leaf from its name-path and shape."""
    tp = mesh.shape.get("tp", 1)
    fsdp = mesh.shape.get("fsdp", 1)
    name = "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    ).lower()

    if len(shape) == 1:
        # bias / norm scale: tiny; replicate.
        return P()
    if "embed" in name and len(shape) == 2:
        if _divisible(shape[0], tp):
            return P("tp", None)
        return P()
    if len(shape) == 2:
        # Per-expert biases (experts, features): experts over ep with
        # the expert kernels they belong to.
        ep = mesh.shape.get("ep", 1)
        if "expert" in name and _divisible(shape[0], ep):
            return P("ep", "tp" if _divisible(shape[1], tp) else None)
        out = "tp" if _divisible(shape[1], tp) else None
        inn = "fsdp" if _divisible(shape[0], fsdp) else None
        return P(inn, out)
    if len(shape) == 4:  # conv HWIO
        out = "tp" if _divisible(shape[3], tp) else None
        return P(None, None, None, out)
    if len(shape) == 3:
        # MoE expert kernels (ops/moe.py): (experts, in, out) — experts
        # over ``ep`` (each ep shard owns whole experts; tokens reach
        # them via the dispatch einsum's all_to_all), out-features over
        # ``tp`` within each expert.  Name-gated like QKV below.
        ep = mesh.shape.get("ep", 1)
        if "expert" in name and _divisible(shape[0], ep):
            out = "tp" if _divisible(shape[2], tp) else None
            return P("ep", None, out)
        # Attention QKV DenseGeneral: (hidden, heads, head_dim) — shard
        # by HEADS (Megatron attention-parallel: each tp shard owns
        # whole heads, so the attention itself needs no collective).
        # Gate on the layer NAME, not just divisibility, so a future
        # 3-D kernel with a different axis layout never silently gets
        # heads-style placement.
        if "qkv" in name:
            # FUSED projection: the head axis is [Q..., K..., V...] —
            # a contiguous tp chunking never respects the section
            # boundaries (slots of Q and K land on one shard), so
            # head-sharding it would force per-layer reshards after
            # the q/k/v slices.  Replicate the head axis; fsdp still
            # shards the hidden axis.  tp>1 attention wanting Megatron
            # head-sharding should build layers with fused_qkv=False.
            inn = "fsdp" if _divisible(shape[0], fsdp) else None
            return P(inn, None, None)
        is_qkv = any(t in name for t in ("query", "key", "value"))
        if is_qkv:
            inn = "fsdp" if _divisible(shape[0], fsdp) else None
            if _divisible(shape[1], tp):
                return P(inn, "tp", None)
            # GQA K/V kernels whose few heads don't divide tp:
            # REPLICATE rather than shard head_dim — q stays
            # heads-sharded, k/v replicated, and the attention still
            # needs no collective (sharding head_dim would force
            # per-layer reshards against the heads-sharded q).
            return P(inn, None, None)
        out = "tp" if _divisible(shape[-1], tp) else None
        return P(None, None, out)
    return P()


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """Pytree of NamedShardings mirroring ``params``."""

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = [
        NamedSharding(mesh, leaf_spec(path, leaf.shape, mesh))
        for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, specs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *, seq_axis: int | None = None) -> NamedSharding:
    """Leading axis over the data axes; optionally a sequence axis over sp.

    ``seq_axis`` is the *positional* axis index of sequence length in the
    batch array (1 for ``(batch, seq)`` token inputs).
    """
    dims: list = [("dp", "fsdp")]
    if seq_axis is not None:
        while len(dims) < seq_axis:
            dims.append(None)
        dims.append("sp" if mesh.shape.get("sp", 1) > 1 else None)
    return NamedSharding(mesh, P(*dims))


def shard_batch(mesh: Mesh, arrays: tuple, *, seq_axes: dict[int, int] | None
                = None) -> tuple:
    """Device-put a tuple of host arrays with batch sharding.

    ``seq_axes`` maps tuple-position → sequence axis index for arrays that
    also shard over sp (token matrices under sequence parallelism).
    """
    seq_axes = seq_axes or {}
    out = []
    for i, arr in enumerate(arrays):
        sh = batch_sharding(mesh, seq_axis=seq_axes.get(i))
        out.append(jax.device_put(arr, sh))
    return tuple(out)
