"""Pipeline parallelism: GPipe-style microbatched stage execution.

Fills the ``pp`` mesh axis (parallel/mesh.py).  The reference scales
only by replicating whole workers (Ray replicas / Horovod rings —
reference: docker-compose.yml:329-347, binary_executor_image/
binary_execution.py:237-292); it has no way to run a model larger than
one worker's memory.  Pipeline stages are the TPU-native answer: layer
stages shard over ``pp``, microbatches stream through the stages, and
activations hop between ICI neighbours via ``ppermute``.

TPU-first design:

- **SPMD, not a scheduler.**  One program runs on every device; the
  stage index is ``lax.axis_index('pp')``.  The GPipe schedule is a
  static loop of ``n_micro + pp - 1`` ticks — every tick each stage
  applies itself to its current microbatch and ``ppermute``s the
  activation to its ICI neighbour.  No host round-trips, no per-stage
  processes: the whole pipeline (fwd + bwd + optimizer) is ONE jitted
  step.
- **Backward for free.**  ``jax.grad`` through ``ppermute`` transposes
  to the reverse permutation, so the backward pipeline (activations
  flowing last→first stage) falls out of AD — no hand-written reverse
  schedule.
- **Bubble accounting.**  Utilisation is n_micro/(n_micro + pp - 1);
  the default n_micro = 2·pp keeps the bubble ≤ 33%.  Stage params are
  stacked ``(pp, ...)`` and sharded ``P('pp')`` so per-device memory is
  layers/pp of the trunk — the model-size axis dp cannot buy.

``sequential_loss`` runs the mathematically identical computation
without the mesh — the oracle the tests pin the schedule against.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import MeshSpec, build_mesh
from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import (
    NeuralEstimator,
    TrainHistory,
)

_MODULE = "learningorchestra_tpu.parallel.pipeline"


class _Embed(nn.Module):
    vocab_size: int
    hidden_dim: int
    max_len: int
    dtype: Any = None  # None = promote (bf16 when the step casts params)

    @nn.compact
    def __call__(self, tokens):
        from learningorchestra_tpu.models.text import embed_tokens

        return embed_tokens(
            tokens.astype(jnp.int32), self.vocab_size, self.hidden_dim,
            self.max_len, self.dtype,
        )


class _Stage(nn.Module):
    """``layers_per_stage`` transformer blocks — the unit one pp rank
    owns.  Every stage has identical structure, so stage params stack
    into one pytree with a leading (pp,) axis sharded over the mesh."""

    hidden_dim: int
    num_heads: int
    mlp_dim: int
    layers_per_stage: int
    causal: bool
    dtype: Any = None  # None = promote (bf16 when the step casts params)

    @nn.compact
    def __call__(self, x, key_mask):
        from learningorchestra_tpu.models.text import TransformerBlock

        for i in range(self.layers_per_stage):
            x = TransformerBlock(
                hidden_dim=self.hidden_dim,
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                causal=self.causal,
                name=f"TransformerBlock_{i}",
            )(x, key_mask=key_mask)
        return x


class _Head(nn.Module):
    hidden_dim: int
    out_dim: int
    kind: str  # 'cls' | 'lm'

    @nn.compact
    def __call__(self, h):
        from learningorchestra_tpu.models.text import cls_head

        h = nn.LayerNorm()(h)
        if self.kind == "lm":
            return nn.Dense(self.out_dim)(h)
        return cls_head(h, self.hidden_dim, self.out_dim)


def gpipe_loss(
    embed_apply,
    stage_apply,
    head_apply,
    loss_fn,
    *,
    n_stages: int,
    n_micro: int,
    axis: str = "pp",
):
    """Per-device GPipe loss for use inside ``shard_map``.

    ``stage_params`` arrives with its (pp,) leading axis already
    sharded away (shape ``(1, ...)``); inputs are this dp-shard's
    batch, replicated across ``pp``.  Returns the pipeline loss psum'd
    to every rank.
    """

    def fn(eparams, sparams, hparams, xb, yb, mb):
        sparams = jax.tree_util.tree_map(lambda l: l[0], sparams)
        idx = lax.axis_index(axis)
        mb_sz = xb.shape[0] // n_micro
        xm = xb.reshape(n_micro, mb_sz, *xb.shape[1:])
        ym = yb.reshape(n_micro, mb_sz, *yb.shape[1:])
        mm = mb.reshape(n_micro, mb_sz)
        key_masks = xm != 0  # (M, mb, T) pad id 0

        # Every rank embeds every microbatch; only rank 0's embedding
        # feeds the pipeline (others get zero cotangent, so embed grads
        # stay correct after the psum below).  Trades pp-1 redundant
        # embed lookups for zero cross-stage plumbing of raw tokens.
        emb = jax.vmap(lambda t: embed_apply(eparams, t))(xm)

        recv = jnp.zeros_like(emb[0])
        outs = []
        right = [(i, i + 1) for i in range(n_stages - 1)]
        for t in range(n_micro + n_stages - 1):
            # Stage s processes microbatch (t - s) at tick t.
            mi = jnp.clip(t - idx, 0, n_micro - 1)
            x_in = jnp.where(idx == 0, emb[jnp.clip(t, 0, n_micro - 1)],
                             recv)
            out = stage_apply(sparams, x_in, key_masks[mi])
            if t >= n_stages - 1:
                outs.append(out)
            if right:
                recv = lax.ppermute(out, axis, right)

        # outs[j] on the LAST rank is microbatch j's trunk output.
        h = jnp.stack(outs)  # (M, mb, T, H)
        logits = jax.vmap(lambda hh: head_apply(hparams, hh))(h)
        flat_logits = logits.reshape(n_micro * mb_sz, *logits.shape[2:])
        flat_y = ym.reshape(n_micro * mb_sz, *ym.shape[2:])
        flat_m = mm.reshape(n_micro * mb_sz)
        loss, metrics = loss_fn(
            flat_logits.astype(jnp.float32), flat_y, flat_m
        )

        # Only the last rank's loss is real; weight by its local mask
        # mass and psum over (dp, pp) for the global masked mean.
        is_last = (idx == n_stages - 1).astype(jnp.float32)
        w = flat_m.sum() * is_last
        axes = ("dp", "fsdp", axis)
        gw = jnp.maximum(lax.psum(w, axes), 1e-9)

        def _avg(v):
            return lax.psum(v * w, axes) / gw

        return _avg(loss), jax.tree_util.tree_map(_avg, metrics)

    return fn


def sequential_loss(embed_apply, stage_apply, head_apply, loss_fn,
                    *, n_stages: int):
    """The pipeline's math without the pipeline — stages applied in
    order on one device.  Correctness oracle + predict path."""

    def fn(eparams, sparams, hparams, xb, yb, mb):
        km = xb != 0
        h = embed_apply(eparams, xb)
        for s in range(n_stages):
            sp = jax.tree_util.tree_map(lambda l: l[s], sparams)
            h = stage_apply(sp, h, km)
        logits = head_apply(hparams, h).astype(jnp.float32)
        return loss_fn(logits, yb, mb)

    return fn


@register(_MODULE)
class PipelinedTransformer:
    """Transformer classifier/LM trained GPipe-parallel over ``pp``.

    fit/evaluate/predict mirror the NeuralEstimator surface so the
    executor layer drives it by reflection (services/executor.py).
    ``num_layers`` must divide evenly into ``pp`` stages.
    """

    def __init__(
        self,
        vocab_size: int = 20000,
        hidden_dim: int = 128,
        num_layers: int = 4,
        num_heads: int = 4,
        mlp_dim: int | None = None,
        max_len: int = 256,
        num_classes: int = 2,
        head: str = "cls",  # 'cls' | 'lm'
        n_microbatches: int | None = None,
        learning_rate: float = 1e-3,
        seed: int = 0,
        mesh: Mesh | None = None,
        pp: int | None = None,
        compute_dtype: str = "bfloat16",
    ):
        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim or hidden_dim * 4
        self.max_len = max_len
        self.num_classes = num_classes
        self.head = head
        self.learning_rate = learning_rate
        self.seed = seed
        self.compute_dtype = compute_dtype
        if mesh is None:
            n = jax.device_count()
            if pp is not None:
                # Explicit pp: honour it or fail loudly, exactly like
                # the explicit-mesh path below.
                stages = pp
                if n % stages:
                    raise ValueError(
                        f"pp={stages} does not divide {n} devices"
                    )
            else:
                stages = min(n, num_layers)
                while num_layers % stages or n % stages:
                    stages -= 1
            mesh = build_mesh(
                MeshSpec(dp=n // stages, pp=stages)
            )
        self.mesh = mesh
        self.pp = mesh.shape["pp"]
        if num_layers % self.pp:
            raise ValueError(
                f"num_layers={num_layers} not divisible by pp={self.pp}"
            )
        self.n_micro = n_microbatches or 2 * self.pp
        self.optimizer = optax.adam(learning_rate)

        causal = head == "lm"
        out_dim = vocab_size if head == "lm" else num_classes
        self._embed = _Embed(vocab_size, hidden_dim, max_len)
        self._stage = _Stage(
            hidden_dim=hidden_dim,
            num_heads=num_heads,
            mlp_dim=self.mlp_dim,
            layers_per_stage=num_layers // self.pp,
            causal=causal,
        )
        self._head = _Head(hidden_dim, out_dim, head)
        self._loss_fn = NeuralEstimator._loss_and_metrics("softmax_ce")
        self.params = None
        self.opt_state = None
        self.history = TrainHistory()
        self._step = None
        self._oracle = None
        self._seq_fwd = None

    # -- init -----------------------------------------------------------------

    def _init_params(self, x0: jnp.ndarray) -> None:
        k0, k1, k2 = jax.random.split(jax.random.PRNGKey(self.seed), 3)
        ep = self._embed.init(k0, x0)
        h0 = self._embed.apply(ep, x0)
        km0 = x0 != 0
        sp = jax.vmap(
            lambda k: self._stage.init(k, h0, km0)
        )(jax.random.split(k1, self.pp))
        hp = self._head.init(k2, h0)
        self.params = self._place_params((ep, sp, hp))
        self.opt_state = jax.jit(
            self.optimizer.init,
        )(self.params)

    def _place_params(self, params: tuple) -> tuple:
        """Placement: embed/head replicated, stage stack over pp."""
        ep, sp, hp = params
        rep = NamedSharding(self.mesh, P())
        stage_sh = jax.tree_util.tree_map(
            lambda l: NamedSharding(
                self.mesh, P("pp", *[None] * (l.ndim - 1))
            ),
            sp,
        )
        return (
            jax.device_put(ep, rep),
            jax.tree_util.tree_map(jax.device_put, sp, stage_sh),
            jax.device_put(hp, rep),
        )

    # -- jitted step ----------------------------------------------------------

    def _build(self):
        mesh = self.mesh
        batch_spec = P(("dp", "fsdp"))
        stage_spec = jax.tree_util.tree_map(
            lambda _: P("pp"), self.params[1]
        )
        pipe = gpipe_loss(
            self._embed.apply, self._stage.apply, self._head.apply,
            self._loss_fn, n_stages=self.pp, n_micro=self.n_micro,
        )
        smapped = jax.shard_map(
            pipe,
            mesh=mesh,
            in_specs=(P(), stage_spec, P(), batch_spec, batch_spec,
                      batch_spec),
            out_specs=(P(), P()),
        )

        from learningorchestra_tpu.train.neural import _param_cast_for

        _pcast = _param_cast_for(
            jnp.bfloat16 if self.compute_dtype == "bfloat16" else None
        )

        def step(params, opt_state, xb, yb, mb):
            def objective(ps):
                # Mixed precision: bf16 compute copy, f32 master
                # weights in the optimizer (train/neural.py contract).
                loss, metrics = smapped(*_pcast(ps), xb, yb, mb)
                return loss, metrics

            grads, metrics = jax.grad(objective, has_aux=True)(params)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics

        self._step = jax.jit(step, donate_argnums=(0, 1))
        self._oracle = jax.jit(sequential_loss(
            self._embed.apply, self._stage.apply, self._head.apply,
            self._loss_fn, n_stages=self.pp,
        ))

    # -- keras-fit surface ----------------------------------------------------

    def fit(self, x, y, epochs: int = 1, batch_size: int = 32,
            shuffle: bool = True, verbose: int = 0,
            checkpoint_dir: str | None = None,
            checkpoint_every: int = 1,
            checkpoint_min_interval_s: float = 60.0,
            resume: bool = True, **_):
        """Same managed in-loop checkpointing contract as
        ``NeuralEstimator.fit``: with ``checkpoint_dir`` set the
        (stage-stacked) state persists every ``checkpoint_every``
        epochs via the shard-aware orbax helper — sharded stage params
        save without a host gather — and an interrupted fit resumes
        from the newest checkpoint (the preemption story, SURVEY §5.4).
        """
        x = np.asarray(x)
        y = np.asarray(y).astype(np.int32)
        # Global batch must split into n_micro microbatches that split
        # over dp; round it DOWN to the nearest legal multiple (never
        # below one quantum) so the effective batch fits the request.
        dp = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        quantum = self.n_micro * dp
        batch_size = max(quantum, (batch_size // quantum) * quantum)
        if self.params is None:
            self._init_params(jnp.asarray(x[:1]))
        if self._step is None:
            self._build()

        start_epoch = 0
        if checkpoint_dir and resume:
            from learningorchestra_tpu.train import checkpoint as ckpt

            loaded = ckpt.resume_or_none(
                checkpoint_dir,
                {"params": self.params, "opt_state": self.opt_state},
            )
            if loaded is not None:
                state, step, past_history = loaded
                # Re-place onto the pipeline shardings: orbax restores
                # each leaf to the TEMPLATE leaf's placement, and
                # scalar optimizer counts can come back single-device,
                # which jit rejects against mesh-placed params.
                self.params = self._place_params(state["params"])
                fresh = jax.jit(self.optimizer.init)(self.params)
                mesh_devices = set(self.mesh.devices.flat)

                def _sh(f):
                    sh = getattr(f, "sharding", None)
                    if sh is not None and \
                            set(sh.device_set) == mesh_devices:
                        return sh
                    # Scalar leaves (adam's count) come off the init
                    # jit on one device; replicate them on the mesh.
                    return NamedSharding(self.mesh, P())

                self.opt_state = jax.tree_util.tree_map(
                    lambda r, f: jax.device_put(r, _sh(f)),
                    state["opt_state"], fresh,
                )
                self.history = TrainHistory(past_history)
                start_epoch = step

        from learningorchestra_tpu.train import checkpoint as ckpt_mod

        last_save = time.monotonic()
        rng = np.random.default_rng(self.seed)
        n = len(x)
        if shuffle:
            # Burn the completed epochs' draws so a resumed run
            # shuffles exactly as the original would at this epoch.
            for _ in range(start_epoch):
                rng.permutation(n)
        for epoch_i in range(start_epoch, epochs):
            order = rng.permutation(n) if shuffle else np.arange(n)
            epoch_metrics = []
            for lo in range(0, n, batch_size):
                idx = order[lo: lo + batch_size]
                if len(idx) < batch_size:  # pad + mask the tail batch
                    pad = batch_size - len(idx)
                    idx = np.concatenate([idx, idx[:1].repeat(pad)])
                    mask = np.concatenate(
                        [np.ones(batch_size - pad, np.float32),
                         np.zeros(pad, np.float32)]
                    )
                else:
                    mask = np.ones(batch_size, np.float32)
                self.params, self.opt_state, metrics = self._step(
                    self.params, self.opt_state,
                    jnp.asarray(x[idx]), jnp.asarray(y[idx]),
                    jnp.asarray(mask),
                )
                epoch_metrics.append(metrics)
            stacked = jax.device_get(epoch_metrics)
            epoch_row = {
                k: float(np.mean([m[k] for m in stacked]))
                for k in stacked[0]
            }
            if "perplexity" in epoch_row:  # raw CE until post-mean exp
                epoch_row["perplexity"] = float(
                    np.exp(epoch_row["perplexity"])
                )
            self.history.append(epoch_row)
            if verbose:
                print(f"pipeline epoch: {self.history['loss'][-1]:.4f}",
                      flush=True)
            if checkpoint_dir and ckpt_mod.should_save(
                epoch_i, epochs, checkpoint_every,
                checkpoint_min_interval_s, last_save,
            ):
                ckpt_mod.save(
                    checkpoint_dir, epoch_i + 1,
                    {"params": self.params,
                     "opt_state": self.opt_state},
                    history=dict(self.history),
                )
                last_save = time.monotonic()
        return self

    _CHUNK = 512  # inference batch: fixed shape -> one compile

    def _forward_chunks(self, x: np.ndarray):
        """Sequential (non-pipelined) forward in fixed-size chunks —
        inference needs no microbatch schedule, and chunking keeps
        activations O(chunk) instead of O(dataset) while the fixed
        chunk shape compiles once."""
        if self._seq_fwd is None:
            def fwd(params, xb):
                ep, sp, hp = params
                km = xb != 0
                h = self._embed.apply(ep, xb)
                for s in range(self.pp):
                    ssp = jax.tree_util.tree_map(lambda l: l[s], sp)
                    h = self._stage.apply(ssp, h, km)
                return self._head.apply(hp, h)

            self._seq_fwd = jax.jit(fwd)
        for lo in range(0, len(x), self._CHUNK):
            chunk = x[lo: lo + self._CHUNK]
            n = len(chunk)
            if n < self._CHUNK:  # pad to the compiled shape (id 0)
                chunk = np.pad(chunk, ((0, self._CHUNK - n), (0, 0)))
            yield np.asarray(
                self._seq_fwd(self.params, jnp.asarray(chunk))
            )[:n]

    def evaluate(self, x, y, **_) -> dict:
        x = np.asarray(x)
        y = np.asarray(y).astype(np.int32)
        if self.params is None:
            raise RuntimeError("evaluate before fit")
        sums: dict = {}
        total = 0
        for lo, logits in zip(range(0, len(x), self._CHUNK),
                              self._forward_chunks(x)):
            yb = jnp.asarray(y[lo: lo + len(logits)])
            _, metrics = self._loss_fn(
                jnp.asarray(logits, jnp.float32), yb,
                jnp.ones(len(logits), jnp.float32),
            )
            for k, v in metrics.items():
                sums[k] = sums.get(k, 0.0) + float(v) * len(logits)
            total += len(logits)
        out = {k: v / max(total, 1) for k, v in sums.items()}
        if "perplexity" in out:  # raw CE until post-mean exp
            out["perplexity"] = float(np.exp(out["perplexity"]))
        return out

    def predict(self, x, **_):
        x = np.asarray(x)
        if self.params is None:
            raise RuntimeError("predict before fit")
        out = np.concatenate(list(self._forward_chunks(x)), axis=0)
        if self.head == "cls":
            return np.argmax(out, -1)
        return out

    # -- persistence ----------------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "history": dict(self.history),
        }

    def load_state_dict(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.history = TrainHistory(state.get("history", {}))
        self._step = None
        self._oracle = None
        self._seq_fwd = None

    def __getstate__(self):
        """dill support (the model service persists instances): drop
        jitted closures and the Mesh (Device handles don't pickle) —
        the mesh rebuilds from its axis sizes on load."""
        d = dict(self.__dict__)
        d["_step"] = None
        d["_oracle"] = None
        d["_seq_fwd"] = None
        d["mesh"] = None
        d["_mesh_shape"] = dict(self.mesh.shape) \
            if self.mesh is not None else None
        if d["params"] is not None:
            d["params"] = jax.device_get(d["params"])
        if d["opt_state"] is not None:
            d["opt_state"] = jax.device_get(d["opt_state"])
        return d

    def __setstate__(self, d):
        shape = d.pop("_mesh_shape", None)
        self.__dict__.update(d)
        if shape is not None:
            self.mesh = build_mesh(MeshSpec.from_dict(shape))
