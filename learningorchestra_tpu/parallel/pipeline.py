"""Pipeline parallelism: GPipe-style microbatched stage execution.

Fills the ``pp`` mesh axis (parallel/mesh.py).  The reference scales
only by replicating whole workers (Ray replicas / Horovod rings —
reference: docker-compose.yml:329-347, binary_executor_image/
binary_execution.py:237-292); it has no way to run a model larger than
one worker's memory.  Pipeline stages are the TPU-native answer: layer
stages shard over ``pp``, microbatches stream through the stages, and
activations hop between ICI neighbours via ``ppermute``.

TPU-first design:

- **SPMD, not a scheduler.**  One program runs on every device; the
  stage index is ``lax.axis_index('pp')``.  The GPipe schedule is a
  static loop of ``n_micro + pp - 1`` ticks — every tick each stage
  applies itself to its current microbatch and ``ppermute``s the
  activation to its ICI neighbour.  No host round-trips, no per-stage
  processes: the whole pipeline (fwd + bwd + optimizer) is ONE jitted
  step.
- **Backward for free.**  ``jax.grad`` through ``ppermute`` transposes
  to the reverse permutation, so the backward pipeline (activations
  flowing last→first stage) falls out of AD — no hand-written reverse
  schedule.
- **Bubble accounting.**  Utilisation is n_micro/(n_micro + pp - 1);
  the default n_micro = 2·pp keeps the bubble ≤ 33%.  Stage params are
  stacked ``(pp, ...)`` and sharded ``P('pp')`` so per-device memory is
  layers/pp of the trunk — the model-size axis dp cannot buy.

``sequential_loss`` runs the mathematically identical computation
without the mesh — the oracle the tests pin the schedule against.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.jobs.cancel import cancel_requested
from learningorchestra_tpu.parallel.mesh import MeshSpec, build_mesh
from learningorchestra_tpu.toolkit.registry import register
from learningorchestra_tpu.train.neural import (
    NeuralEstimator,
    TrainHistory,
)

_MODULE = "learningorchestra_tpu.parallel.pipeline"


class _Embed(nn.Module):
    vocab_size: int
    hidden_dim: int
    max_len: int
    dtype: Any = None  # None = promote (bf16 when the step casts params)

    @nn.compact
    def __call__(self, tokens):
        from learningorchestra_tpu.models.text import embed_tokens

        return embed_tokens(
            tokens.astype(jnp.int32), self.vocab_size, self.hidden_dim,
            self.max_len, self.dtype,
        )


class _Stage(nn.Module):
    """``layers_per_stage`` transformer blocks — the unit one pp rank
    owns.  Every stage has identical structure, so stage params stack
    into one pytree with a leading (pp,) axis sharded over the mesh."""

    hidden_dim: int
    num_heads: int
    mlp_dim: int
    layers_per_stage: int
    causal: bool
    dtype: Any = None  # None = promote (bf16 when the step casts params)

    @nn.compact
    def __call__(self, x, key_mask):
        from learningorchestra_tpu.models.text import TransformerBlock

        for i in range(self.layers_per_stage):
            x = TransformerBlock(
                hidden_dim=self.hidden_dim,
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                causal=self.causal,
                name=f"TransformerBlock_{i}",
            )(x, key_mask=key_mask)
        return x


class _Head(nn.Module):
    hidden_dim: int
    out_dim: int
    kind: str  # 'cls' | 'lm'

    @nn.compact
    def __call__(self, h):
        from learningorchestra_tpu.models.text import cls_head

        h = nn.LayerNorm()(h)
        if self.kind == "lm":
            return nn.Dense(self.out_dim)(h)
        return cls_head(h, self.hidden_dim, self.out_dim)


def gpipe_loss(
    embed_apply,
    stage_apply,
    head_apply,
    loss_fn,
    *,
    n_stages: int,
    n_micro: int,
    axis: str = "pp",
):
    """Per-device GPipe loss for use inside ``shard_map``.

    ``stage_params`` arrives with its (pp,) leading axis already
    sharded away (shape ``(1, ...)``); inputs are this dp-shard's
    batch, replicated across ``pp``.  Returns the pipeline loss psum'd
    to every rank.
    """

    def fn(eparams, sparams, hparams, xb, yb, mb):
        sparams = jax.tree_util.tree_map(lambda l: l[0], sparams)
        idx = lax.axis_index(axis)
        mb_sz = xb.shape[0] // n_micro
        xm = xb.reshape(n_micro, mb_sz, *xb.shape[1:])
        ym = yb.reshape(n_micro, mb_sz, *yb.shape[1:])
        mm = mb.reshape(n_micro, mb_sz)
        key_masks = xm != 0  # (M, mb, T) pad id 0

        # Every rank embeds every microbatch; only rank 0's embedding
        # feeds the pipeline (others get zero cotangent, so embed grads
        # stay correct after the psum below).  Trades pp-1 redundant
        # embed lookups for zero cross-stage plumbing of raw tokens.
        emb = jax.vmap(lambda t: embed_apply(eparams, t))(xm)

        recv = jnp.zeros_like(emb[0])
        outs = []
        right = [(i, i + 1) for i in range(n_stages - 1)]
        for t in range(n_micro + n_stages - 1):
            # Stage s processes microbatch (t - s) at tick t.
            mi = jnp.clip(t - idx, 0, n_micro - 1)
            x_in = jnp.where(idx == 0, emb[jnp.clip(t, 0, n_micro - 1)],
                             recv)
            out = stage_apply(sparams, x_in, key_masks[mi])
            if t >= n_stages - 1:
                outs.append(out)
            if right:
                recv = lax.ppermute(out, axis, right)

        # outs[j] on the LAST rank is microbatch j's trunk output.
        h = jnp.stack(outs)  # (M, mb, T, H)
        logits = jax.vmap(lambda hh: head_apply(hparams, hh))(h)
        flat_logits = logits.reshape(n_micro * mb_sz, *logits.shape[2:])
        flat_y = ym.reshape(n_micro * mb_sz, *ym.shape[2:])
        flat_m = mm.reshape(n_micro * mb_sz)
        loss, metrics = loss_fn(
            flat_logits.astype(jnp.float32), flat_y, flat_m
        )

        # Only the last rank's loss is real; weight by its local mask
        # mass and psum over (dp, pp) for the global masked mean.
        is_last = (idx == n_stages - 1).astype(jnp.float32)
        w = flat_m.sum() * is_last
        axes = ("dp", "fsdp", axis)
        gw = jnp.maximum(lax.psum(w, axes), 1e-9)

        def _avg(v):
            return lax.psum(v * w, axes) / gw

        return _avg(loss), jax.tree_util.tree_map(_avg, metrics)

    return fn


def one_f_one_b_grads(
    embed_apply,
    stage_apply,
    head_apply,
    loss_fn,
    *,
    n_stages: int,
    n_micro: int,
    axis: str = "pp",
):
    """Per-device 1F1B (PipeDream-flush) pipeline step for shard_map:
    returns ``(loss, metrics, grads)`` with the backward INTERLEAVED
    into the schedule instead of left to ``jax.grad``.

    Why it exists: under ``jax.grad``, GPipe's transpose runs as a
    second full pass AFTER the forward loop, so every microbatch's
    residuals stay live through the whole forward — O(n_micro)
    activation memory per rank.  Here each microbatch's backward starts
    the moment it leaves the pipe (last rank: same tick), so a rank
    holds at most ``2·(pp-1-s)`` in-flight inputs — O(pp), independent
    of n_micro.  That converts directly into bubble: at a fixed
    activation budget the 1F1B schedule can run n_micro ≫ pp (bubble
    → (pp-1)/(n_micro+pp-1) → 0) where GPipe's memory wall caps
    n_micro ≈ budget.

    Mechanics (all static Python loops → ONE jitted program, SPMD):

    - macro tick t ∈ [0, n_micro + 2·pp - 3]; rank s forwards
      microbatch ``t - s`` and backwards microbatch
      ``t - 2·pp + 2 + s`` (both masked when out of range);
    - stage inputs are saved in a (2·pp-1)-slot circular buffer; the
      backward RE-APPLIES the stage under ``jax.vjp`` on the saved
      input (rematerialize-in-backward — the standard TPU trade of
      FLOPs for HBM, and what keeps the buffer a stackable tensor
      instead of unstackable residual closures);
    - activations ``ppermute`` right after each forward slot,
      cotangents ``ppermute`` left after each backward slot;
    - the last rank seeds each microbatch's cotangent from the
      head+loss VJP at the forward-completion tick, scaled by
      ``w_m/gw`` so the stitched gradient equals the gradient of the
      same global masked-mean loss as :func:`gpipe_loss`.

    Losses/metrics/grads are psum'd exactly as gpipe's AD would:
    embed/head grads over (dp, fsdp, pp) (replicated out), stage grads
    over (dp, fsdp) only (each rank owns its stage).
    """
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1")
    K = max(1, 2 * n_stages - 1)  # circular input-buffer depth

    def fn(eparams, sparams, hparams, xb, yb, mb):
        sparams = jax.tree_util.tree_map(lambda l: l[0], sparams)
        idx = lax.axis_index(axis)
        P_ = n_stages
        M = n_micro
        mb_sz = xb.shape[0] // M
        xm = xb.reshape(M, mb_sz, *xb.shape[1:])
        ym = yb.reshape(M, mb_sz, *yb.shape[1:])
        mm = mb.reshape(M, mb_sz)
        key_masks = xm != 0  # (M, mb, T) pad id 0

        # Global mask mass — the same normalizer gpipe's psum'd masked
        # mean uses; known upfront so per-microbatch cotangent seeds
        # can be scaled in-schedule.
        gw = jnp.maximum(lax.psum(mb.sum(), ("dp", "fsdp")), 1e-9)

        # Embedding forward ONCE (vmapped over microbatches), its VJP
        # kept for the end: cotangents accumulate per microbatch as
        # rank 0 finishes backwards.
        emb, emb_vjp = jax.vjp(
            lambda ep: jax.vmap(lambda tk: embed_apply(ep, tk))(xm),
            eparams,
        )

        right = [(i, i + 1) for i in range(P_ - 1)]
        left = [(i + 1, i) for i in range(P_ - 1)]
        is_last = idx == P_ - 1
        is_first = idx == 0

        in_buf = jnp.zeros((K, *emb.shape[1:]), emb.dtype)
        demb = jnp.zeros_like(emb)
        dsparams = jax.tree_util.tree_map(jnp.zeros_like, sparams)
        dhparams = jax.tree_util.tree_map(jnp.zeros_like, hparams)
        recv = jnp.zeros_like(emb[0])
        recv_cot = jnp.zeros_like(emb[0])
        loss_acc = jnp.zeros((), jnp.float32)
        w_acc = jnp.zeros((), jnp.float32)
        metrics_acc = None

        def stage_on(km):
            return lambda p, xin: stage_apply(p, xin, km)

        for t in range(M + 2 * P_ - 2):
            # ---- forward slot: rank s, microbatch t - s ----
            m_f = t - idx
            f_valid = ((m_f >= 0) & (m_f < M)).astype(jnp.float32)
            m_fc = jnp.clip(m_f, 0, M - 1)
            km_f = jnp.take(key_masks, m_fc, axis=0)
            x_in = jnp.where(is_first, emb[jnp.clip(t, 0, M - 1)], recv)
            in_buf = in_buf.at[t % K].set(x_in)
            out = stage_apply(sparams, x_in, km_f)
            if right:
                recv = lax.ppermute(out, axis, right)

            # ---- last rank: head + loss + cotangent seed for the
            # backward slot of this SAME tick (1F1B: bwd of m starts
            # the tick its fwd completes) ----
            y_m = jnp.take(ym, m_fc, axis=0)
            mm_m = jnp.take(mm, m_fc, axis=0)

            def head_loss(hp, h, y_m=y_m, mm_m=mm_m):
                logits = head_apply(hp, h).astype(jnp.float32)
                loss, metrics = loss_fn(logits, y_m, mm_m)
                return loss, metrics

            loss_m, hl_vjp, metrics_m = jax.vjp(
                head_loss, hparams, out, has_aux=True
            )
            w_m = mm_m.sum()
            contrib = f_valid * is_last.astype(jnp.float32)
            dhp_m, dh_m = hl_vjp(contrib * w_m / gw)
            dhparams = jax.tree_util.tree_map(
                lambda a, g: a + g, dhparams, dhp_m
            )
            loss_acc = loss_acc + contrib * w_m * loss_m
            w_acc = w_acc + contrib * w_m
            scaled = jax.tree_util.tree_map(
                lambda v: contrib * w_m * v, metrics_m
            )
            metrics_acc = scaled if metrics_acc is None else \
                jax.tree_util.tree_map(
                    lambda a, v: a + v, metrics_acc, scaled
                )

            # ---- backward slot: rank s, microbatch t - 2P + 2 + s ----
            m_b = t - 2 * P_ + 2 + idx
            b_valid = ((m_b >= 0) & (m_b < M)).astype(jnp.float32)
            m_bc = jnp.clip(m_b, 0, M - 1)
            km_b = jnp.take(key_masks, m_bc, axis=0)
            # Rank s forwarded m_b at tick m_b + s = t - 2(P-1-s).
            slot = jnp.mod(t - 2 * (P_ - 1) + 2 * idx, K)
            x_saved = jnp.take(in_buf, slot, axis=0)
            # Cotangents arrive f32 (head_loss upcasts; the where-
            # promote makes stage INPUTS f32 while outputs may be
            # bf16) — cast to this stage's OUTPUT dtype, exactly the
            # cast AD's promote/astype transposes apply on the gpipe
            # path.
            cot_in = jnp.where(is_last, dh_m, recv_cot).astype(
                out.dtype
            )
            _, s_vjp = jax.vjp(stage_on(km_b), sparams, x_saved)
            dsp_m, dx = s_vjp(cot_in)
            dsparams = jax.tree_util.tree_map(
                lambda a, g: a + b_valid * g, dsparams, dsp_m
            )
            dx = dx * b_valid
            # Cast into the buffer dtype: demb is emb-dtype (bf16 under
            # mixed precision) while dx is the f32-promoted input
            # cotangent — a mixed-dtype scatter-add is a future error.
            demb = demb.at[m_bc].add(
                (dx * is_first.astype(jnp.float32)).astype(demb.dtype)
            )
            if left:
                recv_cot = lax.ppermute(dx, axis, left)

        # demb varies over pp (only rank 0 contributed, via
        # axis_index masking) but the embed primal was pp-invariant;
        # psum over pp broadcasts rank 0's cotangent everywhere, making
        # the vjp input's replication type match the primal's — and
        # every rank then computes the identical embed grad.
        (deparams,) = emb_vjp(lax.psum(demb, axis))

        all_axes = ("dp", "fsdp", axis)
        gsum = lambda v: lax.psum(v, all_axes)  # noqa: E731
        gw_all = jnp.maximum(gsum(w_acc), 1e-9)
        loss = gsum(loss_acc) / gw_all
        metrics = jax.tree_util.tree_map(
            lambda v: gsum(v) / gw_all, metrics_acc
        )
        # No explicit grad psums: shard_map's replication-typing makes
        # each jax.vjp transpose psum cotangents onto device-INVARIANT
        # inputs automatically (an invariant param used by varying data
        # transposes to a cross-device sum).  deparams/dhparams come
        # out fully invariant (global sums); dsp_m came out dp-summed
        # per pp rank.  Adding our own psums here double-counts —
        # measured 4x/8x on a dp=4,pp=2 mesh before this comment.
        grads = (
            deparams,
            jax.tree_util.tree_map(lambda g: g[None], dsparams),
            dhparams,
        )
        return loss, metrics, grads

    return fn


def sequential_loss(embed_apply, stage_apply, head_apply, loss_fn,
                    *, n_stages: int):
    """The pipeline's math without the pipeline — stages applied in
    order on one device.  Correctness oracle + predict path."""

    def fn(eparams, sparams, hparams, xb, yb, mb):
        km = xb != 0
        h = embed_apply(eparams, xb)
        for s in range(n_stages):
            sp = jax.tree_util.tree_map(lambda l: l[s], sparams)
            h = stage_apply(sp, h, km)
        logits = head_apply(hparams, h).astype(jnp.float32)
        return loss_fn(logits, yb, mb)

    return fn


@register(_MODULE)
class PipelinedTransformer:
    """Transformer classifier/LM trained GPipe-parallel over ``pp``.

    fit/evaluate/predict mirror the NeuralEstimator surface so the
    executor layer drives it by reflection (services/executor.py).
    ``num_layers`` must divide evenly into ``pp`` stages.
    """

    # Opt in to the executor's managed checkpoint-dir injection so a
    # service-path fit checkpoints (and SIGKILL-resumes) per stage.
    supports_managed_checkpoints = True

    def __init__(
        self,
        vocab_size: int = 20000,
        hidden_dim: int = 128,
        num_layers: int = 4,
        num_heads: int = 4,
        mlp_dim: int | None = None,
        max_len: int = 256,
        num_classes: int = 2,
        head: str = "cls",  # 'cls' | 'lm'
        n_microbatches: int | None = None,
        learning_rate: float = 1e-3,
        seed: int = 0,
        mesh: Mesh | None = None,
        pp: int | None = None,
        compute_dtype: str = "bfloat16",
        schedule: str | None = None,  # 'gpipe' | '1f1b' | 'mpmd'
    ):
        from learningorchestra_tpu.config import get_config

        self.vocab_size = vocab_size
        self.hidden_dim = hidden_dim
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_dim = mlp_dim or hidden_dim * 4
        self.max_len = max_len
        self.num_classes = num_classes
        self.head = head
        self.learning_rate = learning_rate
        self.seed = seed
        self.compute_dtype = compute_dtype
        mpmd_cfg = get_config().mpmd
        if schedule is None:
            # Deployment-default schedule (LO_TPU_MPMD_SCHEDULE): lets
            # an operator flip a fleet to MPMD dispatch without every
            # client spelling the parameter.
            schedule = mpmd_cfg.schedule or "gpipe"
        if schedule not in ("gpipe", "1f1b", "mpmd"):
            raise ValueError(
                "schedule must be 'gpipe', '1f1b' or 'mpmd', "
                f"got {schedule!r}"
            )
        self.schedule = schedule
        if n_microbatches is None and mpmd_cfg.n_micro > 0:
            n_microbatches = mpmd_cfg.n_micro
        if mesh is None:
            n = jax.device_count()
            if pp is not None:
                # Explicit pp: honour it or fail loudly, exactly like
                # the explicit-mesh path below.
                stages = pp
                if n % stages:
                    raise ValueError(
                        f"pp={stages} does not divide {n} devices"
                    )
            else:
                stages = min(n, num_layers)
                while num_layers % stages or n % stages:
                    stages -= 1
            mesh = build_mesh(
                MeshSpec(dp=n // stages, pp=stages)
            )
        self.mesh = mesh
        self.pp = mesh.shape["pp"]
        if num_layers % self.pp:
            raise ValueError(
                f"num_layers={num_layers} not divisible by pp={self.pp}"
            )
        self.n_micro = n_microbatches or 2 * self.pp
        self.optimizer = optax.adam(learning_rate)
        # Declarative spec → per-stage MPMD optimizer programs share
        # compile-cache entries ACROSS jobs (an opaque-object key never
        # matches another instance's; compile_cache.py).
        self._optimizer_spec = {"name": "adam"}

        causal = head == "lm"
        out_dim = vocab_size if head == "lm" else num_classes
        self._embed = _Embed(vocab_size, hidden_dim, max_len)
        self._stage = _Stage(
            hidden_dim=hidden_dim,
            num_heads=num_heads,
            mlp_dim=self.mlp_dim,
            layers_per_stage=num_layers // self.pp,
            causal=causal,
        )
        self._head = _Head(hidden_dim, out_dim, head)
        self._loss_fn = NeuralEstimator._loss_and_metrics("softmax_ce")
        self.params = None
        self.opt_state = None
        self.history = TrainHistory()
        self._step = None
        self._oracle = None
        self._seq_fwd = None
        self._mpmd = None

    # -- init -----------------------------------------------------------------

    def _engine(self):
        """The MPMD host dispatcher (parallel/mpmd.py), built lazily —
        it holds Device handles and cached program refs, so it drops on
        pickle and rebuilds here on first use."""
        if self._mpmd is None:
            from learningorchestra_tpu.parallel.mpmd import MPMDEngine

            self._mpmd = MPMDEngine(self)
        return self._mpmd

    def _init_params(self, x0: jnp.ndarray) -> None:
        k0, k1, k2 = jax.random.split(jax.random.PRNGKey(self.seed), 3)
        ep = self._embed.init(k0, x0)
        h0 = self._embed.apply(ep, x0)
        km0 = x0 != 0
        sp = jax.vmap(
            lambda k: self._stage.init(k, h0, km0)
        )(jax.random.split(k1, self.pp))
        hp = self._head.init(k2, h0)
        if self.schedule == "mpmd":
            # Stage-partitioned layout: the engine splits the stacked
            # stage stack, commits each partition to its stage device,
            # and inits per-partition optimizer states.
            self.params = (ep, sp, hp)
            self._engine().ensure_placed()
            return
        self.params = self._place_params((ep, sp, hp))
        self.opt_state = jax.jit(
            self.optimizer.init,
        )(self.params)

    def _place_params(self, params: tuple) -> tuple:
        """Placement: embed/head replicated, stage stack over pp."""
        ep, sp, hp = params
        rep = NamedSharding(self.mesh, P())
        stage_sh = jax.tree_util.tree_map(
            lambda l: NamedSharding(
                self.mesh, P("pp", *[None] * (l.ndim - 1))
            ),
            sp,
        )
        return (
            jax.device_put(ep, rep),
            jax.tree_util.tree_map(jax.device_put, sp, stage_sh),
            jax.device_put(hp, rep),
        )

    # -- jitted step ----------------------------------------------------------

    def _build(self):
        mesh = self.mesh
        batch_spec = P(("dp", "fsdp"))
        stage_spec = jax.tree_util.tree_map(
            lambda _: P("pp"), self.params[1]
        )

        from learningorchestra_tpu.train.neural import _param_cast_for

        _pcast = _param_cast_for(
            jnp.bfloat16 if self.compute_dtype == "bfloat16" else None
        )

        if self.schedule == "1f1b":
            pipe = one_f_one_b_grads(
                self._embed.apply, self._stage.apply, self._head.apply,
                self._loss_fn, n_stages=self.pp, n_micro=self.n_micro,
            )
            smapped = jax.shard_map(
                pipe,
                mesh=mesh,
                in_specs=(P(), stage_spec, P(), batch_spec, batch_spec,
                          batch_spec),
                out_specs=(P(), P(), (P(), stage_spec, P())),
            )

            def step(params, opt_state, xb, yb, mb):
                # The schedule computes its own gradients (backward
                # interleaved per microbatch); grads arrive in compute
                # dtype and cast back to f32 master precision — the
                # same cast-transpose jax.grad applies on the gpipe
                # path.
                loss, metrics, grads = smapped(*_pcast(params), xb, yb,
                                               mb)
                grads = jax.tree_util.tree_map(
                    lambda g, p: g.astype(p.dtype), grads, params
                )
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params
                )
                params = optax.apply_updates(params, updates)
                return params, opt_state, metrics
        else:
            pipe = gpipe_loss(
                self._embed.apply, self._stage.apply, self._head.apply,
                self._loss_fn, n_stages=self.pp, n_micro=self.n_micro,
            )
            smapped = jax.shard_map(
                pipe,
                mesh=mesh,
                in_specs=(P(), stage_spec, P(), batch_spec, batch_spec,
                          batch_spec),
                out_specs=(P(), P()),
            )

            def step(params, opt_state, xb, yb, mb):
                def objective(ps):
                    # Mixed precision: bf16 compute copy, f32 master
                    # weights in the optimizer (train/neural.py
                    # contract).
                    loss, metrics = smapped(*_pcast(ps), xb, yb, mb)
                    return loss, metrics

                grads, metrics = jax.grad(objective, has_aux=True)(
                    params
                )
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params
                )
                params = optax.apply_updates(params, updates)
                return params, opt_state, metrics

        self._step = jax.jit(step, donate_argnums=(0, 1))
        self._oracle = jax.jit(sequential_loss(
            self._embed.apply, self._stage.apply, self._head.apply,
            self._loss_fn, n_stages=self.pp,
        ))

    def _restore_placed(self, state: dict) -> None:
        """Shared resume re-placement: orbax restores each leaf to the
        TEMPLATE leaf's placement, and scalar optimizer counts can come
        back single-device, which jit rejects against mesh-placed
        params — re-pin both onto the pipeline shardings."""
        self.params = self._place_params(state["params"])
        fresh = jax.jit(self.optimizer.init)(self.params)
        mesh_devices = set(self.mesh.devices.flat)

        def _sh(f):
            sh = getattr(f, "sharding", None)
            if sh is not None and set(sh.device_set) == mesh_devices:
                return sh
            # Scalar leaves (adam's count) come off the init jit on
            # one device; replicate them on the mesh.
            return NamedSharding(self.mesh, P())

        self.opt_state = jax.tree_util.tree_map(
            lambda r, f: jax.device_put(r, _sh(f)),
            state["opt_state"], fresh,
        )

    def _batch_pass(self, xs, ys, order, batch_size):
        """Run the pipelined train step over ``order`` in batch_size
        slices (tail batch padded + masked); returns the DEVICE metric
        dicts and each batch's real-row weight — callers device_get at
        their own granularity (per epoch in-memory, per shard when
        streaming) so tunnel round-trips stay amortized."""
        mpmd = self.schedule == "mpmd"
        engine = self._engine() if mpmd else None
        metrics_list, weights = [], []
        # Accumulates across calls (streaming fits pass one shard per
        # call); the epoch loops zero it per epoch for attribution.
        self._epoch_batches = getattr(self, "_epoch_batches", 0)
        for lo in range(0, len(order), batch_size):
            idx = order[lo: lo + batch_size]
            if len(idx) < batch_size:
                pad = batch_size - len(idx)
                idx = np.concatenate([idx, idx[:1].repeat(pad)])
                mask = np.concatenate([
                    np.ones(batch_size - pad, np.float32),
                    np.zeros(pad, np.float32),
                ])
            else:
                mask = np.ones(batch_size, np.float32)
            if mpmd:
                # Host-dispatched 1F1B over per-stage programs; the
                # engine mutates params/opt_state in place of the
                # donate-and-reassign the jitted step does.
                m, w = engine.train_batch(xs[idx], ys[idx], mask)
                metrics_list.append(m)
                weights.append(w)
            else:
                self.params, self.opt_state, m = self._step(
                    self.params, self.opt_state,
                    jnp.asarray(xs[idx]), jnp.asarray(ys[idx]),
                    jnp.asarray(mask),
                )
                metrics_list.append(m)
                weights.append(float(mask.sum()))
            self._epoch_batches += 1
        return metrics_list, weights

    @staticmethod
    def _weighted_update(totals, metrics_list, weights):
        """device_get + mask-weighted accumulation (a padded tail
        batch must not count like a full one); returns weight added."""
        stacked = jax.device_get(metrics_list)
        for m, w in zip(stacked, weights):
            for k, v in m.items():
                totals[k] = totals.get(k, 0.0) + float(v) * w
        return sum(weights)

    @staticmethod
    def _finish_row(totals, wsum):
        row = {k: v / max(wsum, 1e-9) for k, v in totals.items()}
        if "perplexity" in row:  # raw CE until post-mean exp
            row["perplexity"] = float(np.exp(row["perplexity"]))
        return row

    # -- shared fit plumbing --------------------------------------------------

    def _batch_quantum(self) -> int:
        """Smallest legal global batch: n_micro microbatches, times
        the dp replication for the SPMD schedules.  MPMD ignores dp —
        one device per stage, scale via bigger microbatches."""
        if self.schedule == "mpmd":
            return self.n_micro
        return self.n_micro * (
            self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        )

    def _ckpt_resume(self, checkpoint_dir) -> int:
        """Resume from ``checkpoint_dir`` if it holds a checkpoint;
        returns the epoch to continue from (0 = fresh).  MPMD resumes
        every stage partition from its newest COMMON step
        (parallel/mpmd.py); the SPMD schedules restore the single
        stacked state."""
        if self.schedule == "mpmd":
            loaded = self._engine().resume_checkpoint(checkpoint_dir)
            if loaded is None:
                return 0
            step, past_history = loaded
            self.history = TrainHistory(past_history)
            return step
        from learningorchestra_tpu.train import checkpoint as ckpt

        loaded = ckpt.resume_or_none(
            checkpoint_dir,
            {"params": self.params, "opt_state": self.opt_state},
        )
        if loaded is None:
            return 0
        state, step, past_history = loaded
        self._restore_placed(state)
        self.history = TrainHistory(past_history)
        return step

    def _ckpt_save(self, checkpoint_dir, step: int,
                   *, async_save: bool) -> None:
        if self.schedule == "mpmd":
            self._engine().save_checkpoint(
                checkpoint_dir, step, dict(self.history),
                async_save=async_save,
            )
            return
        from learningorchestra_tpu.train import checkpoint as ckpt

        opt_state = self.opt_state
        if opt_state is None:
            # restore-best dropped the moments: checkpoint the
            # restored params with FRESH moments, else resume=True
            # would replay the last periodic save's pre-restore params
            # (same contract as train/neural.py).
            opt_state = jax.jit(self.optimizer.init)(self.params)
            self.opt_state = opt_state
        ckpt.save(
            checkpoint_dir, step,
            {"params": self.params, "opt_state": opt_state},
            history=dict(self.history),
            async_save=async_save,
        )

    def _ckpt_finalize(self, checkpoint_dir) -> None:
        from learningorchestra_tpu.train import checkpoint as ckpt

        if self.schedule == "mpmd":
            self._engine().finalize_checkpoints(checkpoint_dir)
        ckpt.finalize_async(checkpoint_dir)

    def _record_epoch_obs(self, epoch_i: int, epoch_s: float) -> None:
        """Per-epoch trace spans + device-time attribution.  MPMD adds
        one ``mpmd.stage`` span per pipeline stage (host dispatch
        seconds — where the schedule spent its enqueue time) and books
        the epoch against the job cost ledger with the aggregate
        per-stage flops, collectives excluded."""
        from learningorchestra_tpu.obs import tracing

        attrs: dict = {}
        if self.schedule == "mpmd" and self._mpmd is not None:
            engine = self._mpmd
            n_batches = getattr(self, "_epoch_batches", 0)
            engine.attribute_epoch(epoch_s, n_batches)
            attrs = engine.epoch_cost_attrs(epoch_s, n_batches)
            for s, secs in enumerate(engine.pop_stage_seconds()):
                tracing.record_span(
                    "mpmd.stage", secs, stage=s, epoch=epoch_i
                )
        tracing.record_span("epoch", epoch_s, epoch=epoch_i, **attrs)

    # -- keras-fit surface ----------------------------------------------------

    def fit(self, x, y, epochs: int = 1, batch_size: int = 32,
            shuffle: bool = True, verbose: int = 0,
            checkpoint_dir: str | None = None,
            checkpoint_every: int = 1,
            checkpoint_min_interval_s: float = 60.0,
            resume: bool = True, checkpoint_async: bool = True,
            callbacks: list | None = None, early_stopping=None, **_):
        """Same managed in-loop checkpointing contract as
        ``NeuralEstimator.fit``: with ``checkpoint_dir`` set the
        (stage-stacked) state persists every ``checkpoint_every``
        epochs via the shard-aware orbax helper — sharded stage params
        save without a host gather — and an interrupted fit resumes
        from the newest checkpoint (the preemption story, SURVEY §5.4).

        Sharded-dataset views stream shard by shard (the beyond-RAM
        contract every fit surface carries, train/neural.py
        ``_fit_streaming``).
        """
        from learningorchestra_tpu.train.neural import (
            _is_sharded,
            build_stop_callbacks,
        )

        callbacks = build_stop_callbacks(self, callbacks,
                                         early_stopping)
        if _is_sharded(x) or _is_sharded(y):
            return self._fit_streaming(
                x, y, epochs=epochs, batch_size=batch_size,
                shuffle=shuffle, verbose=verbose,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                checkpoint_min_interval_s=checkpoint_min_interval_s,
                resume=resume, checkpoint_async=checkpoint_async,
                callbacks=callbacks,
            )
        x = np.asarray(x)
        y = np.asarray(y).astype(np.int32)
        # Global batch must split into n_micro microbatches that split
        # over dp; round it DOWN to the nearest legal multiple (never
        # below one quantum) so the effective batch fits the request.
        quantum = self._batch_quantum()
        batch_size = max(quantum, (batch_size // quantum) * quantum)
        if self.params is None:
            self._init_params(jnp.asarray(x[:1]))
        if self._step is None and self.schedule != "mpmd":
            self._build()

        start_epoch = 0
        if checkpoint_dir and resume:
            start_epoch = self._ckpt_resume(checkpoint_dir)

        from learningorchestra_tpu import faults
        from learningorchestra_tpu.train import checkpoint as ckpt_mod

        last_save = time.monotonic()
        rng = np.random.default_rng(self.seed)
        n = len(x)
        if shuffle:
            # Burn the completed epochs' draws so a resumed run
            # shuffles exactly as the original would at this epoch.
            for _ in range(start_epoch):
                rng.permutation(n)
        try:
            for epoch_i in range(start_epoch, epochs):
                if cancel_requested():
                    # Engine-side cancellation (deadline watchdog or
                    # bounded shutdown drain): wind down like an
                    # early stop.
                    self.stop_training = True
                    break
                faults.hit("train.epoch")
                t0 = time.perf_counter()
                self._epoch_batches = 0
                order = rng.permutation(n) if shuffle else np.arange(n)
                totals: dict = {}
                wsum = self._weighted_update(
                    totals, *self._batch_pass(x, y, order, batch_size)
                )
                epoch_row = self._finish_row(totals, wsum)
                self.history.append(epoch_row)
                self._record_epoch_obs(
                    epoch_i, time.perf_counter() - t0
                )
                if verbose:
                    print(f"pipeline epoch: {self.history['loss'][-1]:.4f}",
                          flush=True)
                for cb in callbacks or []:
                    if callable(cb):
                        cb(epoch_i, epoch_row, self)
                if checkpoint_dir and ckpt_mod.should_save(
                    epoch_i, epochs, checkpoint_every,
                    checkpoint_min_interval_s, last_save,
                    stopped=self.stop_training,
                ):
                    self._ckpt_save(
                        checkpoint_dir, epoch_i + 1,
                        async_save=checkpoint_async,
                    )
                    last_save = time.monotonic()
                if self.stop_training:
                    break
        finally:
            if checkpoint_dir:
                # The last async save must be durable when fit
                # returns — exception paths included.
                self._ckpt_finalize(checkpoint_dir)
        return self

    def _fit_streaming(
        self, x, y, *, epochs, batch_size, shuffle, verbose,
        checkpoint_dir, checkpoint_every, checkpoint_min_interval_s,
        resume, checkpoint_async, callbacks: list | None = None,
    ) -> "PipelinedTransformer":
        """Shard-streaming pipelined fit: the same microbatched step,
        fed shard by shard with IO-thread prefetch — token datasets
        bigger than host RAM train through the pp mesh unchanged."""
        import concurrent.futures

        from learningorchestra_tpu.store import sharded as sh

        x, y = sh.resolve_xy_views(x, y)
        # Column memory for a later predict/evaluate on the bare
        # dataset (same contract as NeuralEstimator).
        self._sharded_fit_cols = list(x.cols)
        ds = x.dataset
        quantum = self._batch_quantum()
        batch_size = max(quantum, (batch_size // quantum) * quantum)
        if self.params is None:
            self._init_params(jnp.asarray(np.asarray(x.head(1))))
        if self._step is None and self.schedule != "mpmd":
            self._build()

        start_epoch = 0
        if checkpoint_dir and resume:
            start_epoch = self._ckpt_resume(checkpoint_dir)

        from learningorchestra_tpu import faults
        from learningorchestra_tpu.train import checkpoint as ckpt_mod

        def load(k: int):
            xs = np.asarray(x.load_shard(k))
            ys = np.asarray(y.load_shard(k)).astype(np.int32)
            return xs, ys

        last_save = time.monotonic()
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="shard-io"
        ) as io:
            try:
                for epoch_i in range(start_epoch, epochs):
                    if cancel_requested():
                        # Same contract as the in-memory loop.
                        self.stop_training = True
                        break
                    faults.hit("train.epoch")  # see in-memory loop
                    t0 = time.perf_counter()
                    self._epoch_batches = 0
                    order = (
                        np.random.default_rng(
                            [self.seed, 3, epoch_i]
                        ).permutation(ds.n_shards)
                        if shuffle else np.arange(ds.n_shards)
                    )
                    totals: dict = {}
                    wsum = 0.0
                    nxt = io.submit(load, int(order[0]))
                    for pos, k in enumerate(order):
                        xs, ys = nxt.result()
                        if pos + 1 < len(order):
                            nxt = io.submit(load, int(order[pos + 1]))
                        inner = (
                            np.random.default_rng(
                                [self.seed, 7 + epoch_i, pos]
                            ).permutation(len(xs))
                            if shuffle else np.arange(len(xs))
                        )
                        # device_get per SHARD: bounded retained
                        # buffers for beyond-RAM datasets, without
                        # per-batch tunnel round-trips.
                        wsum += self._weighted_update(
                            totals,
                            *self._batch_pass(
                                xs, ys, inner, batch_size
                            ),
                        )
                    epoch_row = self._finish_row(totals, wsum)
                    self.history.append(epoch_row)
                    self._record_epoch_obs(
                        epoch_i, time.perf_counter() - t0
                    )
                    if verbose:
                        print(
                            "pipeline epoch: "
                            f"{self.history['loss'][-1]:.4f}",
                            flush=True,
                        )
                    for cb in callbacks or []:
                        if callable(cb):
                            cb(epoch_i, epoch_row, self)
                    if checkpoint_dir and ckpt_mod.should_save(
                        epoch_i, epochs, checkpoint_every,
                        checkpoint_min_interval_s, last_save,
                        stopped=self.stop_training,
                    ):
                        self._ckpt_save(
                            checkpoint_dir, epoch_i + 1,
                            async_save=checkpoint_async,
                        )
                        last_save = time.monotonic()
                    if self.stop_training:
                        break
            finally:
                if checkpoint_dir:
                    self._ckpt_finalize(checkpoint_dir)
        return self

    _CHUNK = 512  # inference batch: fixed shape -> one compile

    def _forward_chunks(self, x: np.ndarray):
        """Sequential (non-pipelined) forward in fixed-size chunks —
        inference needs no microbatch schedule, and chunking keeps
        activations O(chunk) instead of O(dataset) while the fixed
        chunk shape compiles once."""
        if self.schedule == "mpmd":
            engine = self._engine()
            for lo in range(0, len(x), self._CHUNK):
                chunk = x[lo: lo + self._CHUNK]
                n = len(chunk)
                if n < self._CHUNK:  # pad to the compiled shape
                    chunk = np.pad(
                        chunk, ((0, self._CHUNK - n), (0, 0))
                    )
                yield np.asarray(engine.forward_logits(chunk))[:n]
            return
        if self._seq_fwd is None:
            def fwd(params, xb):
                ep, sp, hp = params
                km = xb != 0
                h = self._embed.apply(ep, xb)
                for s in range(self.pp):
                    ssp = jax.tree_util.tree_map(lambda l: l[s], sp)
                    h = self._stage.apply(ssp, h, km)
                return self._head.apply(hp, h)

            self._seq_fwd = jax.jit(fwd)
        for lo in range(0, len(x), self._CHUNK):
            chunk = x[lo: lo + self._CHUNK]
            n = len(chunk)
            if n < self._CHUNK:  # pad to the compiled shape (id 0)
                chunk = np.pad(chunk, ((0, self._CHUNK - n), (0, 0)))
            yield np.asarray(
                self._seq_fwd(self.params, jnp.asarray(chunk))
            )[:n]

    def evaluate(self, x, y, **_) -> dict:
        from learningorchestra_tpu.train.neural import _is_sharded

        if _is_sharded(x) or _is_sharded(y):
            from learningorchestra_tpu.store import sharded as sh

            x, y = sh.resolve_xy_views(x, y)
            dsx = x.dataset
            acc = sh.WeightedMetrics()
            for k in range(dsx.n_shards):
                acc.add(
                    self.evaluate(x.load_shard(k), y.load_shard(k)),
                    dsx.shard_rows[k],
                )
            return acc.result()
        x = np.asarray(x)
        y = np.asarray(y).astype(np.int32)
        if self.params is None:
            raise RuntimeError("evaluate before fit")
        sums: dict = {}
        total = 0
        for lo, logits in zip(range(0, len(x), self._CHUNK),
                              self._forward_chunks(x)):
            yb = jnp.asarray(y[lo: lo + len(logits)])
            _, metrics = self._loss_fn(
                jnp.asarray(logits, jnp.float32), yb,
                jnp.ones(len(logits), jnp.float32),
            )
            for k, v in metrics.items():
                sums[k] = sums.get(k, 0.0) + float(v) * len(logits)
            total += len(logits)
        out = {k: v / max(total, 1) for k, v in sums.items()}
        if "perplexity" in out:  # raw CE until post-mean exp
            out["perplexity"] = float(np.exp(out["perplexity"]))
        return out

    def predict(self, x, **_):
        from learningorchestra_tpu.train.neural import _is_sharded

        if _is_sharded(x):
            from learningorchestra_tpu.store import sharded as sh

            if isinstance(x, sh.ShardedDataset):
                cols = getattr(self, "_sharded_fit_cols", None)
                view = x.view(cols) if cols and all(
                    c in x.fields for c in cols
                ) else x.view(x.fields)
            else:
                view = x
            return np.concatenate([
                self.predict(view.load_shard(k))
                for k in range(view.dataset.n_shards)
            ], axis=0)
        x = np.asarray(x)
        if self.params is None:
            raise RuntimeError("predict before fit")
        out = np.concatenate(list(self._forward_chunks(x)), axis=0)
        if self.head == "cls":
            return np.argmax(out, -1)
        return out

    # -- persistence ----------------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "history": dict(self.history),
        }

    def load_state_dict(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.history = TrainHistory(state.get("history", {}))
        self._step = None
        self._oracle = None
        self._seq_fwd = None
        self._mpmd = None  # host state → engine re-places on next use

    def __getstate__(self):
        """dill support (the model service persists instances): drop
        jitted closures and the Mesh (Device handles don't pickle) —
        the mesh rebuilds from its axis sizes on load."""
        d = dict(self.__dict__)
        d["_step"] = None
        d["_oracle"] = None
        d["_seq_fwd"] = None
        d["_mpmd"] = None
        d["mesh"] = None
        d["_mesh_shape"] = dict(self.mesh.shape) \
            if self.mesh is not None else None
        if d["params"] is not None:
            d["params"] = jax.device_get(d["params"])
        if d["opt_state"] is not None:
            d["opt_state"] = jax.device_get(d["opt_state"])
        return d

    def __setstate__(self, d):
        shape = d.pop("_mesh_shape", None)
        self.__dict__.update(d)
        if shape is not None:
            self.mesh = build_mesh(MeshSpec.from_dict(shape))
