"""Coordinator-driven multi-host training launch.

The reference's flagship distributed path ships a model definition to
Ray workers and runs ``train`` on each, gathering rank-0 weights
(reference: microservices/binary_executor_image/binary_execution.py:
237-292, training_function/train_function.py:53-139).  Here the same
shape is a *registered* coordinator function (never pickled code over
the wire, SURVEY §5.8) that every ``HostAgent`` runs with its assigned
``rank``/``world_size``:

1. join the global JAX runtime (``jax.distributed.initialize`` — ICI
   within a slice, DCN across hosts);
2. build the estimator from the toolkit registry and the global mesh
   from the request's mesh spec;
3. run ``DistributedTrainer.fit`` — ONE SPMD program over every host's
   devices (gradients all-reduce inside the jitted step; there is no
   host-side ring to rendezvous);
4. rank 0 persists the trained state (the reference's rank-0
   ``get_weights`` contract, minus weight lists through the control
   plane — state goes straight to the artifact store).

Every process passes the same host-side dataset (the reference's
convention: each Horovod worker loaded the data); the trainer hands
each process only its addressable shards on device.
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

from learningorchestra_tpu.parallel.coordinator import (
    init_multihost,
    register_function,
)

# jax.distributed.initialize may only run once per process; remember the
# address we joined so a second job on the same agent can proceed (same
# cluster) or fail loudly (different cluster).
_joined: dict[str, Any] = {}


def _join(jax_coordinator: str, world_size: int, rank: int) -> None:
    if _joined:
        if _joined.get("addr") != jax_coordinator:
            raise RuntimeError(
                f"agent already joined JAX cluster {_joined['addr']!r}; "
                f"cannot join {jax_coordinator!r}"
            )
        return
    init_multihost(jax_coordinator, world_size, rank)
    _joined.update({"addr": jax_coordinator, "rank": rank})


@register_function("lo.multihost_fit")
def multihost_fit(
    rank: int,
    world_size: int,
    *,
    jax_coordinator: str,
    module_path: str,
    class_name: str,
    class_parameters: dict | None = None,
    mesh: dict | None = None,
    data: dict,
    fit: dict | None = None,
    out: dict | None = None,
) -> dict:
    """Join the global mesh and run one sharded fit; see module docstring.

    ``data``: {"x": <.npy path>, "y": <.npy path>} — every host loads the
    full arrays.  ``out``: {"volume_root", "artifact_type", "name"} —
    rank 0 persists the trained estimator there.  Returns the training
    history (every rank returns it; the coordinator keys results by
    rank, so callers read rank 0's).
    """
    import jax

    _join(jax_coordinator, world_size, rank)

    from learningorchestra_tpu.parallel.distributed import DistributedTrainer
    from learningorchestra_tpu.parallel.mesh import MeshSpec, build_mesh
    from learningorchestra_tpu.toolkit import registry

    factory = registry.resolve(module_path, class_name)
    est = factory(**(class_parameters or {}))

    spec = MeshSpec.from_dict(mesh or {"dp": jax.device_count()})
    trainer = DistributedTrainer(est, mesh=build_mesh(spec))

    x = np.load(data["x"], allow_pickle=False)
    y = np.load(data["y"], allow_pickle=False)
    trainer.fit(x, y, **(fit or {}))

    if out and jax.process_index() == 0:
        from learningorchestra_tpu.store.volumes import VolumeStorage

        storage = VolumeStorage(out["volume_root"])
        storage.save_object(
            out.get("artifact_type", "train/tensorflow"), out["name"], est
        )

    return {
        "rank": rank,
        "process_index": jax.process_index(),
        "history": {k: list(v) for k, v in trainer.history.items()},
    }


def agent_main(
    coordinator_address: str,
    agent_id: str | None = None,
    poll_interval: float = 0.05,
) -> None:
    """Foreground host-agent loop — the per-host entry point a deploy
    runs next to the TPU VM (replaces the reference's ray-worker
    container, docker-compose.yml:329-347).  Importing this module
    registers the multihost functions before serving."""
    from learningorchestra_tpu.parallel.coordinator import HostAgent

    agent = HostAgent(
        coordinator_address,
        agent_id or f"agent-{os.getpid()}",
    )
    agent.serve(poll_interval=poll_interval)
    try:
        while True:
            import time

            time.sleep(3600)
    except KeyboardInterrupt:
        agent.stop()
