"""Coordinator-driven multi-host training launch.

The reference's flagship distributed path ships a model definition to
Ray workers and runs ``train`` on each, gathering rank-0 weights
(reference: microservices/binary_executor_image/binary_execution.py:
237-292, training_function/train_function.py:53-139).  Here the same
shape is a *registered* coordinator function (never pickled code over
the wire, SURVEY §5.8) that every ``HostAgent`` runs with its assigned
``rank``/``world_size``:

1. join the global JAX runtime (``jax.distributed.initialize`` — ICI
   within a slice, DCN across hosts);
2. build the estimator from the toolkit registry and the global mesh
   from the request's mesh spec;
3. run ``DistributedTrainer.fit`` — ONE SPMD program over every host's
   devices (gradients all-reduce inside the jitted step; there is no
   host-side ring to rendezvous);
4. rank 0 persists the trained state (the reference's rank-0
   ``get_weights`` contract, minus weight lists through the control
   plane — state goes straight to the artifact store).

Every process passes the same host-side dataset (the reference's
convention: each Horovod worker loaded the data); the trainer hands
each process only its addressable shards on device.
"""

from __future__ import annotations

import os
import time
from typing import Any

import numpy as np

from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.parallel.coordinator import (
    init_multihost,
    register_function,
)

logger = get_logger("launch")


def _job_orphaned(job_meta: dict | None) -> bool:
    """True when the coordinator no longer knows this job — it
    restarted and lost the record (404), so the submitting client's
    wait has already failed and a re-run may be in flight.  Any other
    answer (including an unreachable coordinator, which is transient)
    counts as NOT orphaned: dropping a valid fit's output on a
    network blip would be worse than the race this guards."""
    if not job_meta or not job_meta.get("job_id"):
        return False
    import urllib.error

    from learningorchestra_tpu.parallel.coordinator import http_json

    try:
        http_json(
            f"{job_meta['coordinator']}/jobs/{job_meta['job_id']}"
        )
        return False
    except urllib.error.HTTPError as exc:
        return exc.code == 404
    except OSError:
        return False

# jax.distributed.initialize may only run once per process; remember the
# address we joined so a second job on the same agent can proceed (same
# cluster) or fail loudly (different cluster).
_joined: dict[str, Any] = {}


def _join(jax_coordinator: str, world_size: int, rank: int) -> None:
    if _joined:
        if _joined.get("addr") != jax_coordinator:
            raise RuntimeError(
                f"agent already joined JAX cluster {_joined['addr']!r}; "
                f"cannot join {jax_coordinator!r}"
            )
        return
    init_multihost(jax_coordinator, world_size, rank)
    _joined.update({"addr": jax_coordinator, "rank": rank})


def _negotiate_rendezvous(
    rank: int, job_meta: dict | None, timeout: float = 120.0
) -> str:
    """Rank 0 binds a port and PUBLISHES its address through the task
    coordinator; other ranks poll the job record for it.  This keeps
    rank assignment free (first agent to lease wins rank 0) without any
    statically-configured rank-0 host — the address follows the rank.
    """
    import socket

    from learningorchestra_tpu.parallel.coordinator import http_json

    if not job_meta or not job_meta.get("job_id"):
        raise RuntimeError(
            "no jax_coordinator configured and no coordinator "
            "back-channel available to negotiate one"
        )
    base, job_id = job_meta["coordinator"], job_meta["job_id"]
    if rank == 0:
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        host = socket.gethostbyname(socket.gethostname())
        address = f"{host}:{port}"
        http_json(f"{base}/jobs/{job_id}/rendezvous", {"address": address})
        return address
    deadline = time.time() + timeout
    while time.time() < deadline:
        _, job = http_json(f"{base}/jobs/{job_id}")
        if job.get("rendezvous"):
            return job["rendezvous"]
        time.sleep(0.2)
    raise TimeoutError(
        f"rank {rank}: no rendezvous published for job {job_id} "
        f"within {timeout}s"
    )


@register_function("lo.multihost_fit")
def multihost_fit(
    rank: int,
    world_size: int,
    *,
    jax_coordinator: str | None = None,
    job_meta: dict | None = None,
    module_path: str | None = None,
    class_name: str | None = None,
    class_parameters: dict | None = None,
    estimator_volume: dict | None = None,
    compile_spec: dict | None = None,
    mesh: dict | None = None,
    data: dict,
    fit: dict | None = None,
    out: dict | None = None,
) -> dict:
    """Join the global mesh and run one sharded fit; see module docstring.

    The estimator comes from the toolkit registry
    (``module_path``/``class_name``/``class_parameters``) or from a
    shared artifact volume (``estimator_volume`` =
    {"volume_root", "artifact_type", "name"} — how the REST service
    ships the parent model, which every deploy mounts on every host).
    ``data``: {"x": <.npy path>, "y": <.npy path>} — every host loads the
    full arrays.  ``compile_spec``: declarative optimizer/loss overrides;
    ``#`` expressions evaluate through the DSL sandbox (no store access
    on agents).  ``out``: {"volume_root", "artifact_type", "name"} —
    rank 0 persists the trained estimator there.  Returns the training
    history (every rank returns it; the coordinator keys results by
    rank, so callers read rank 0's).
    """
    import jax

    if jax_coordinator is None:
        jax_coordinator = _negotiate_rendezvous(rank, job_meta)
    _join(jax_coordinator, world_size, rank)

    from learningorchestra_tpu.parallel.distributed import DistributedTrainer
    from learningorchestra_tpu.parallel.mesh import MeshSpec, build_mesh

    if estimator_volume:
        from learningorchestra_tpu.store.volumes import VolumeStorage

        est = VolumeStorage(estimator_volume["volume_root"]).read_object(
            estimator_volume["artifact_type"], estimator_volume["name"]
        )
    else:
        from learningorchestra_tpu.toolkit import registry

        factory = registry.resolve(module_path, class_name)
        est = factory(**(class_parameters or {}))

    if compile_spec:
        from learningorchestra_tpu import dsl

        class _NoStore:
            def load(self, name):  # pragma: no cover - guard path
                raise KeyError(
                    f"agents cannot load store artifacts (${name})"
                )

        est.compile(**dsl.resolve_params(compile_spec, _NoStore()))

    spec = MeshSpec.from_dict(mesh or {"dp": jax.device_count()})
    shard_seq = (mesh or {}).get("shardSequence")
    trainer = DistributedTrainer(
        est, mesh=build_mesh(spec),
        shard_sequence=None if shard_seq is None else bool(shard_seq),
    )

    x = np.load(data["x"], allow_pickle=False)
    y = np.load(data["y"], allow_pickle=False)
    fit_kwargs = dict(fit or {})
    if "vx" in data and "vy" in data:
        fit_kwargs["validation_data"] = (
            np.load(data["vx"], allow_pickle=False),
            np.load(data["vy"], allow_pickle=False),
        )
    trainer.fit(x, y, **fit_kwargs)

    if out and jax.process_index() == 0:
        if _job_orphaned(job_meta):
            # Generation fence: the coordinator restarted and forgot
            # this job, so the client's wait already failed and may
            # have started a PATCH re-run targeting the SAME artifact
            # name.  A zombie write here would race the re-run
            # last-writer-wins — drop the output instead; the history
            # still returns for the (already-failed) record.
            logger.warning(kv(
                event="orphaned_fit_output_dropped",
                job=(job_meta or {}).get("job_id"),
                artifact=out["name"],
            ))
        else:
            from learningorchestra_tpu.store.volumes import VolumeStorage

            storage = VolumeStorage(out["volume_root"])
            storage.save_object(
                out.get("artifact_type", "train/tensorflow"),
                out["name"], est,
            )

    return {
        "rank": rank,
        "process_index": jax.process_index(),
        "history": {k: list(v) for k, v in trainer.history.items()},
    }


def agent_main(
    coordinator_address: str,
    agent_id: str | None = None,
    poll_interval: float = 0.05,
) -> None:
    """Foreground host-agent loop — the per-host entry point a deploy
    runs next to the TPU VM (replaces the reference's ray-worker
    container, docker-compose.yml:329-347).  Importing this module
    registers the multihost functions before serving."""
    from learningorchestra_tpu.parallel.coordinator import HostAgent

    agent = HostAgent(
        coordinator_address,
        agent_id or f"agent-{os.getpid()}",
    )
    agent.serve(poll_interval=poll_interval)
    try:
        while True:
            import time

            time.sleep(3600)
    except KeyboardInterrupt:
        agent.stop()
