"""Named device meshes.

The reference's unit of scale is a Ray worker process joined to a Gloo
ring (reference: microservices/binary_executor_image/server.py:16-17 —
``num_workers=1, cpus_per_worker=2``; docker-compose.yml:329-347 scales
``ray-worker`` replicas).  The TPU-native unit of scale is a **mesh axis**:

- ``dp``   — data parallelism: batch split, gradients psum'd over ICI;
- ``fsdp`` — data parallelism with parameters sharded along it (ZeRO-3
  style), all-gathered per layer by XLA when used;
- ``pp``   — pipeline parallelism: GPipe microbatch stages, activations
  ppermute'd between ICI neighbours (parallel/pipeline.py);
- ``ep``   — expert parallelism: MoE expert weights sharded along it,
  tokens all_to_all'd to their experts (ops/moe.py);
- ``tp``   — tensor parallelism: feature-dim matmul sharding;
- ``sp``   — sequence/context parallelism: ring attention over this axis.

All six axes always exist (size 1 when unused) so any strategy is a
sharding annotation, never a rewrite — SURVEY §2.4's design requirement.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "fsdp", "pp", "ep", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Total size must divide the device count."""

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.pp * self.ep * self.tp * self.sp

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXES}

    @staticmethod
    def from_dict(d: dict) -> "MeshSpec":
        return MeshSpec(**{a: int(d.get(a, 1)) for a in AXES})


def default_spec(n_devices: int | None = None) -> MeshSpec:
    """Pure data parallelism over every device — the reference's only
    gradient-parallel strategy (SURVEY §2.4), here the safe default."""
    n = n_devices if n_devices is not None else jax.device_count()
    return MeshSpec(dp=n)


def build_mesh(
    spec: MeshSpec | None = None, devices: list | None = None
) -> Mesh:
    """Arrange devices into a 6-axis named mesh.

    Axis order is (dp, fsdp, pp, ep, tp, sp) from outermost to
    innermost: ``jax.devices()`` enumerates devices in ICI-neighbor
    order, so inner axes (tp/sp — latency-sensitive, per-layer
    collectives — and ep's per-MoE-layer all_to_all) land on
    ICI-adjacent chips; pp communicates only microbatch activations at
    stage boundaries and sits outside them; dp (one psum per step,
    bandwidth-tolerant) spans the outer dimension and, multi-slice, the
    DCN boundary.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    spec = spec or default_spec(devs.size)
    validate_spec(spec)
    if spec.size > devs.size or devs.size % spec.size:
        raise ValueError(
            f"mesh spec {spec} (size {spec.size}) does not fit "
            f"{devs.size} devices"
        )
    if spec.size < devs.size:
        # Fold spare devices into dp — scale-out without re-speccing.
        spec = dataclasses.replace(spec, dp=spec.dp * (devs.size // spec.size))
    shape = tuple(getattr(spec, a) for a in AXES)
    return Mesh(devs[: spec.size].reshape(shape), AXES)


def spec_for_devices(n_devices: int, *, model_parallel: int = 1,
                     sequence_parallel: int = 1) -> MeshSpec:
    """Split ``n_devices`` into dp × tp × sp with dp taking the rest."""
    inner = model_parallel * sequence_parallel
    if n_devices % inner:
        raise ValueError(
            f"{n_devices} devices not divisible by tp*sp={inner}"
        )
    return MeshSpec(
        dp=n_devices // inner, tp=model_parallel, sp=sequence_parallel
    )


def validate_spec(spec: MeshSpec) -> None:
    for axis in AXES:
        size = getattr(spec, axis)
        if size < 1 or size != int(size):
            raise ValueError(f"mesh axis {axis} must be a positive int")
    # Ring attention rotates sp blocks; power-of-two keeps the ring
    # permutation balanced on physical ICI tori.
    if spec.sp > 1 and spec.sp & (spec.sp - 1):
        raise ValueError("sp axis should be a power of two")
