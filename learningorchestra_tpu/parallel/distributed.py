"""DistributedTrainer — the mesh-sharded training loop.

Replaces the reference's flagship distributed path (reference:
microservices/binary_executor_image/binary_execution.py:237-292 —
``RayExecutor.run(train)`` fanning a Horovod/Gloo ring over Ray workers,
rank-0 weights shipped back as lists).  Here the same request shape
(epochs / batch_size / validation, SURVEY §3.3) drives one jitted train
step over a named mesh:

- the batch enters sharded over ``(dp, fsdp)`` — each device sees its
  slice only; gradients emerge psum'd over ICI because XLA's SPMD
  partitioner sees replicated params meeting sharded data (no host ring,
  no weight serialization);
- parameters/optimizer state live sharded in HBM between steps and are
  gathered to host only at checkpoint boundaries (``jax.device_get`` at
  job edges, SURVEY §5.4);
- an epoch is one ``lax.scan`` over device-resident batches — Python
  dispatch cost is per-epoch, not per-batch (the reference pays a Ray RPC
  + Gloo rendezvous per job and Python dispatch per batch).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.jobs.cancel import cancel_requested
from learningorchestra_tpu.obs import tracing as obs_tracing
from learningorchestra_tpu.parallel.mesh import MeshSpec, build_mesh
from learningorchestra_tpu.parallel.sharding import param_shardings
from learningorchestra_tpu.toolkit.base import as_array
from learningorchestra_tpu.train import compile_cache
from learningorchestra_tpu.train.neural import (
    NeuralEstimator,
    TrainHistory,
    _batch_data,
    _NoShuffle,
    build_resident_epoch_fns,
)


class DistributedTrainer:
    """Mesh-sharded fit/evaluate over a ``NeuralEstimator``'s model.

    ``batch_size`` below is the GLOBAL batch size (split across the data
    axes), matching the reference's semantics where ``model.fit`` on each
    Horovod worker saw the full user-specified batch per replica only by
    accident of num_workers=1.
    """

    def __init__(
        self,
        estimator: NeuralEstimator,
        spec: MeshSpec | None = None,
        mesh: Mesh | None = None,
        shard_sequence: bool | None = None,
    ):
        self.estimator = estimator
        self.mesh = mesh if mesh is not None else build_mesh(spec)
        if self.mesh.shape.get("pp", 1) > 1:
            # Nothing in this trainer shards over pp, so pp > 1 would
            # replicate every rank's work pp-fold with no speedup.
            raise ValueError(
                "DistributedTrainer does not use the pp axis; "
                "pipeline parallelism is parallel.pipeline."
                "PipelinedTransformer"
            )
        if shard_sequence is None:
            # Auto: an sp>1 mesh only means anything if the token axis
            # is actually sharded.
            shard_sequence = self.mesh.shape.get("sp", 1) > 1
        self.shard_sequence = shard_sequence
        self._bind_depth = 0
        # Mesh-sharded live state, re-anchored every epoch so callbacks
        # (EarlyStopping restore-best) can snapshot/replace it exactly
        # as they do on the single-device estimator.
        self.params = None
        self.opt_state = None
        self.history = TrainHistory()
        self._epoch_fn = None
        self._eval_fn = None
        self._loss_kind = None
        self._fn_key = None

    @contextlib.contextmanager
    def _mesh_bound(self):
        """Mesh-aware models (ring attention over sp) get the mesh bound
        for the duration of a trainer call ONLY — left bound, the
        estimator's own single-device predict/evaluate would hit
        shard_map divisibility errors on arbitrary batch shapes."""
        est = self.estimator
        bindable = hasattr(est, "bind_mesh")
        if bindable and self._bind_depth == 0:
            est.bind_mesh(self.mesh)
        self._bind_depth += 1
        try:
            yield
        finally:
            self._bind_depth -= 1
            if bindable and self._bind_depth == 0:
                est.bind_mesh(None)

    # -- placement ----------------------------------------------------------

    @property
    def data_axes(self) -> int:
        return self.mesh.shape["dp"] * self.mesh.shape["fsdp"]

    def _data_sharding(self, ndim: int, tokens: bool) -> NamedSharding:
        """(n_batches, global_bs, ...) epoch arrays: shard the per-batch
        batch axis (1); optionally the sequence axis (2) over sp."""
        dims: list = [None, ("dp", "fsdp")]
        if (
            tokens
            and self.shard_sequence
            and ndim > 2
            and self.mesh.shape.get("sp", 1) > 1
        ):
            dims.append("sp")
        while len(dims) < ndim:
            dims.append(None)
        return NamedSharding(self.mesh, P(*dims))

    def _put_global(self, arr, sharding):
        """Host array → global sharded device array.

        Single-process: plain ``device_put``.  Multi-process (every host
        holds the full host-side value — the same convention as the
        reference, where each Horovod worker loaded the dataset;
        binary_execution.py:251-268 shipped the model the same way):
        ``make_array_from_callback`` hands each process exactly its
        addressable shards, so the global array spans all hosts' devices
        without any host ever holding more than its slice on device.
        """
        arr = np.asarray(arr)
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    def _put_tree(self, tree, shardings):
        return jax.tree_util.tree_map(
            lambda a, sh: self._put_global(a, sh), tree, shardings
        )

    def _place_state(self) -> tuple:
        est = self.estimator
        psh = param_shardings(est.params, self.mesh)
        params = self._put_tree(jax.device_get(est.params), psh)
        # Optimizer state inherits param shardings through propagation.
        fresh = self._fresh_moments(params)
        if est.opt_state is not None and jax.tree_util.tree_structure(
            est.opt_state
        ) == jax.tree_util.tree_structure(fresh):
            # Resume accumulated moments (continuation training / PATCH
            # re-run) instead of zeroing them — same contract as the
            # single-device fit (neural.py fit resumes self.opt_state).
            mesh_devices = set(self.mesh.devices.flat)

            def _sh(leaf):
                sh = getattr(leaf, "sharding", None)
                if sh is not None and set(sh.device_set) == mesh_devices:
                    return sh
                # Scalar leaves (e.g. adam's step count) come off the init
                # jit on one device; they must be replicated on the mesh.
                return NamedSharding(self.mesh, P())

            opt_sh = jax.tree_util.tree_map(_sh, fresh)
            opt_state = self._put_tree(
                jax.device_get(est.opt_state), opt_sh
            )
        else:
            opt_state = fresh
        return params, opt_state

    def _check_seq_divisible(self, x: np.ndarray) -> None:
        """Friendly error for sequence lengths the sp axis can't shard
        (otherwise shard_map fails with an opaque divisibility error)."""
        sp = self.mesh.shape.get("sp", 1)
        if (
            self.shard_sequence and sp > 1 and x.ndim > 1
            and np.issubdtype(x.dtype, np.integer) and x.shape[1] % sp
        ):
            raise ValueError(
                f"sequence length {x.shape[1]} not divisible by sp={sp}"
            )

    # -- step construction --------------------------------------------------

    def _build(self, loss_kind: str, shuffle: bool, cost_args=None):
        est = self.estimator
        dtype = jnp.bfloat16 if est.compute_dtype == "bfloat16" else None
        # Same jitted loss/grad/update math as the single-device path
        # (train/neural.py), with the carry donated so params/opt_state
        # update in place in HBM, over a device-RESIDENT sharded dataset:
        # upload happens once per fit, each epoch permutes batch order on
        # device from a PRNG key (host traffic per epoch = key + metric
        # scalars, VERDICT r1 weak item 3).
        #
        # Resolved through the process-wide compiled-program cache,
        # keyed by mesh axis names + device assignment on top of the
        # architecture spec: a re-submitted distributed job on the SAME
        # mesh re-binds the traced program; a different mesh (or a
        # changed device set) can never serve a stale executable.
        # Mesh-aware modules (bind_mesh) carry their bound mesh as a
        # module field, so their fingerprint shifts with the binding.
        from learningorchestra_tpu.train.neural import _cached_program

        # ``cost_args`` (a shape-avatar thunk, see _cost_args below)
        # rides the build-once path into the cost plane (obs/costs.py)
        # so mesh programs land ANALYZED FLOPs/HBM ledger entries like
        # the single-device epoch programs, instead of the un-analyzed
        # fallback rows get_or_build notes on its own; ``want_cost``
        # hands the entry back for per-epoch device-time attribution.
        fns, cost = _cached_program(
            "resident_epoch_fns", est, loss_kind,
            shapes=(bool(shuffle),),
            mesh=(
                compile_cache.mesh_fingerprint(self.mesh),
                bool(self.shard_sequence),
            ),
            donate=True,
            builder=lambda: build_resident_epoch_fns(
                est.module,
                est.optimizer,
                est._loss_and_metrics(loss_kind),
                dtype,
                shuffle=shuffle,
                donate=True,
            ),
            cost_args=cost_args,
            want_cost=True,
        )
        # Same attribute the single-device fit uses, so the shared
        # span/ledger helpers (_attribute_epoch_cost,
        # _epoch_cost_attrs) see mesh fits identically.  Kept on the
        # trainer too: the fit loop re-stamps the estimator each
        # epoch, so an interleaved single-device fit can't leave its
        # own program's entry attributed to mesh epochs.
        self._epoch_cost = est._device_epoch_cost = cost
        return fns

    def _cost_args(self, x, y_arr, batch_size: int):
        """Shape-avatar thunk for the epoch program's cost probe:
        epoch(params, opt_state, xs, ys, ms, key) argument shapes,
        computed WITHOUT batching or placing anything (eval_shape for
        the moments, _batch_data's shape math for the epoch arrays).
        Lowering is global/unsharded — the ledger entry carries the
        whole mesh's per-epoch FLOPs, cross-shard collectives
        excluded."""
        import math as _math

        def thunk():
            est = self.estimator
            n = x.shape[0]
            nb = max(1, _math.ceil(n / batch_size))
            xs = jax.ShapeDtypeStruct(
                (nb, batch_size) + tuple(x.shape[1:]), x.dtype
            )
            ys = jax.ShapeDtypeStruct(
                (nb, batch_size) + tuple(y_arr.shape[1:]), y_arr.dtype
            )
            ms = jax.ShapeDtypeStruct((nb, batch_size), np.float32)
            opt_state = est.opt_state
            if opt_state is None:
                # Avatars only — nothing allocates.
                opt_state = jax.eval_shape(
                    est.optimizer.init, est.params
                )
            return (
                est.params, opt_state, xs, ys, ms,
                jax.random.PRNGKey(est.seed),
            )

        return thunk

    def _ensure_fns(self, loss_kind: str, shuffle: bool,
                    cost_args=None) -> None:
        # _opt_version (not id(optimizer)): object ids can be reused
        # after GC, which would silently serve a stale compiled step.
        key = (loss_kind, bool(shuffle),
               getattr(self.estimator, "_opt_version", 0))
        if self._epoch_fn is None or self._fn_key != key:
            self._epoch_fn, self._eval_fn = self._build(
                loss_kind, bool(shuffle), cost_args=cost_args
            )
            self._fn_key = key
            self._loss_kind = loss_kind

    def _fresh_moments(self, params):
        """Optimizer state initialized for ``params`` under jit, so
        each leaf's state inherits the param's mesh sharding through
        propagation — the ONE re-init used by state placement and the
        restore-best moments-dropped paths."""
        return jax.jit(self.estimator.optimizer.init)(params)

    def _hand_back(self, params, opt_state) -> None:
        """Trained sharded state → host pytrees on the estimator, so
        the artifact contract (any step re-executable from the stored
        binary, SURVEY §5.4) holds regardless of which path trained it.
        Multi-process: fsdp/tp shards live on other hosts — all-gather
        across processes (the rank-0-persists analogue of the reference
        returning rank-0 weights, binary_execution.py:270-272, except
        every host gets a consistent copy).  ``opt_state=None``
        (restore-best dropped the moments) passes through: the next
        fit re-inits them, matching the single-device contract."""
        est = self.estimator
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            est.params = jax.tree_util.tree_map(
                np.asarray,
                multihost_utils.process_allgather(params, tiled=True),
            )
            est.opt_state = None if opt_state is None else (
                jax.tree_util.tree_map(
                    np.asarray,
                    multihost_utils.process_allgather(
                        opt_state, tiled=True
                    ),
                )
            )
        else:
            est.params = jax.device_get(params)
            est.opt_state = (
                None if opt_state is None else jax.device_get(opt_state)
            )

    # -- public surface -----------------------------------------------------

    def fit(
        self,
        x,
        y,
        epochs: int = 1,
        batch_size: int = 64,
        validation_data: tuple | None = None,
        shuffle: bool = True,
        verbose: int = 0,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        checkpoint_min_interval_s: float = 60.0,
        resume: bool = True,
        accumulate_steps: int = 1,
        checkpoint_async: bool = True,
        callbacks: list | None = None,
        early_stopping=None,
        **_,
    ) -> "DistributedTrainer":
        """Same managed in-loop checkpointing contract as the
        single-device ``NeuralEstimator.fit`` — sharded state gathers to
        host at save points (``jax.device_get``), so a preempted
        distributed job resumes on any mesh shape.

        ``accumulate_steps`` mirrors the single-device knob (gradient
        accumulation via optax.MultiSteps).  Set EXPLICITLY per fit: a
        prior single-device fit's accumulation never leaks in — the
        default resets to plain stepping.

        ``callbacks``/``early_stopping`` mirror the single-device
        surface: callbacks run per epoch as ``cb(epoch, metrics,
        trainer)`` and may set ``trainer.stop_training = True``.
        ``restoreBestWeights`` works here too: the best epoch's params
        are snapshotted DEVICE-SIDE as a sharded copy (``jnp.copy``
        preserves each leaf's mesh sharding — no host gather, no
        resharding) and rolled back on stop; optimizer moments are
        dropped exactly as on the single-device path (they belong to
        later epochs)."""
        from learningorchestra_tpu.train.neural import _is_sharded

        from learningorchestra_tpu.train.neural import (
            build_stop_callbacks,
        )

        callbacks = build_stop_callbacks(self, callbacks, early_stopping)
        if _is_sharded(x) or _is_sharded(y):
            return self._fit_streaming(
                x, y, epochs=epochs, batch_size=batch_size,
                validation_data=validation_data, shuffle=shuffle,
                verbose=verbose, checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                checkpoint_min_interval_s=checkpoint_min_interval_s,
                resume=resume, accumulate_steps=accumulate_steps,
                checkpoint_async=checkpoint_async, callbacks=callbacks,
            )
        est = self.estimator
        # Explicit (re)configuration each fit: no silent inheritance of
        # a wrapper left by an earlier single-device fit, and the fn
        # cache below keys on the resulting optimizer identity.
        est._set_accumulation(accumulate_steps)
        x = np.asarray(as_array(x))
        y_arr = np.asarray(y if not hasattr(y, "to_numpy") else y.to_numpy())
        if y_arr.ndim == 2 and y_arr.shape[1] == 1:
            y_arr = y_arr.reshape(-1)
        loss_kind = est._resolve_loss(y_arr)
        y_arr = y_arr.astype(
            np.int32 if loss_kind == "softmax_ce" else np.float32
        )
        if batch_size % self.data_axes:
            raise ValueError(
                f"global batch_size {batch_size} not divisible by "
                f"dp*fsdp={self.data_axes}"
            )
        tokens = np.issubdtype(x.dtype, np.integer)
        self._check_seq_divisible(x)
        if validation_data is not None:
            self._check_seq_divisible(np.asarray(validation_data[0]))

        start_epoch = 0
        try:
            with self._mesh_bound():
                if est.params is None:
                    est._init_params(jnp.asarray(x[:1]))
                self._ensure_fns(
                    loss_kind, shuffle,
                    cost_args=self._cost_args(x, y_arr, batch_size),
                )

                params, opt_state = self._place_state()
                if checkpoint_dir and resume:
                    from learningorchestra_tpu.train import checkpoint as ckpt

                    # Sharded restore: the placed (mesh-sharded) state is the
                    # template, so orbax loads each shard straight onto its
                    # device — no host-side full-state materialization, and
                    # the saving mesh shape need not match this one.
                    loaded = ckpt.load_latest(
                        checkpoint_dir,
                        {"params": params, "opt_state": opt_state},
                    )
                    if loaded is not None:
                        state, step, past_history = loaded
                        params = state["params"]
                        opt_state = state["opt_state"]
                        self.history = TrainHistory(past_history)
                        start_epoch = step

                # Upload the epoch-batched dataset ONCE, sharded over the
                # data axes; epochs below reshuffle batch order on device.
                rng = np.random.default_rng(est.seed)
                xb, yb, mb = _batch_data(
                    x, y_arr, batch_size, rng if shuffle else _NoShuffle()
                )
                n_samples = xb.shape[0] * xb.shape[1]
                xs = self._put_global(xb, self._data_sharding(xb.ndim, tokens))
                ys = self._put_global(yb, self._data_sharding(yb.ndim, False))
                ms = self._put_global(mb, self._data_sharding(mb.ndim, False))
                root_key = jax.random.PRNGKey(est.seed)
                last_save = time.monotonic()
                ran = 0  # epochs executed THIS call (early stop may cut short)
                for epoch_i in range(start_epoch, epochs):
                    if cancel_requested():
                        # Engine-side cancellation (deadline watchdog
                        # or bounded shutdown drain): wind down like
                        # an early stop.
                        self.stop_training = True
                        break
                    ran += 1
                    t0 = time.perf_counter()
                    params, opt_state, metrics = self._epoch_fn(
                        params, opt_state, xs, ys, ms,
                        jax.random.fold_in(root_key, epoch_i),
                    )
                    # One host transfer for all metric scalars (replicated
                    # outputs, so this is process-local even multi-host).
                    metrics = {
                        k: float(v)
                        for k, v in jax.device_get(metrics).items()
                    }
                    dt = time.perf_counter() - t0
                    metrics["epoch_time"] = dt
                    metrics["samples_per_sec"] = n_samples / dt
                    # Device-time attribution + flops/MFU span attrs
                    # through the SAME helpers as the single-device
                    # fit (the cost probe above stamped the mesh
                    # program's ledger entry on the estimator).
                    from learningorchestra_tpu.train.neural import (
                        _attribute_epoch_cost,
                        _epoch_cost_attrs,
                    )

                    est._device_epoch_cost = getattr(
                        self, "_epoch_cost", None
                    )
                    _attribute_epoch_cost(est, dt)
                    epoch_cost_attrs = _epoch_cost_attrs(est, dt)
                    if validation_data is not None:
                        vx, vy = validation_data
                        metrics.update(
                            {
                                f"val_{k}": v
                                for k, v in self.evaluate(
                                    vx, vy, batch_size=batch_size,
                                    _params=params,
                                ).items()
                            }
                        )
                    self.history.append(metrics)
                    # Trace span per epoch (step + metric transfer +
                    # validation), same contract as the single-device
                    # fit: the job's span tree shows where the
                    # distributed fit's time went, not one opaque
                    # trainer_fit interval.  Single contextvar read
                    # when no trace is active.
                    obs_tracing.record_span(
                        "epoch", time.perf_counter() - t0,
                        epoch=epoch_i, distributed=True,
                        **epoch_cost_attrs,
                    )
                    # Callbacks run before the checkpoint decision so an
                    # early stop still gets its "final epoch" save —
                    # through the ONE shared policy (should_save).
                    # Re-anchor the live sharded state on the trainer
                    # first: EarlyStopping restore-best snapshots
                    # self.params (a device-side sharded jnp.copy) and
                    # on stop replaces it, dropping the moments.
                    self.params, self.opt_state = params, opt_state
                    for cb in callbacks or []:
                        if callable(cb):
                            cb(epoch_i, metrics, self)
                    params, opt_state = self.params, self.opt_state
                    if opt_state is None and not self.stop_training:
                        # A callback rolled params back but training
                        # continues: fresh moments for the new state.
                        opt_state = self._fresh_moments(params)
                        self.opt_state = opt_state
                    from learningorchestra_tpu.train import (
                        checkpoint as ckpt,
                    )

                    if checkpoint_dir and ckpt.should_save(
                        epoch_i, epochs, checkpoint_every,
                        checkpoint_min_interval_s, last_save,
                        stopped=self.stop_training,
                    ):
                        save_opt = opt_state
                        if save_opt is None:
                            # restore-best dropped the moments: persist
                            # the restored params with FRESH moments so
                            # resume never replays pre-restore state
                            # (same contract as the single-device fit).
                            save_opt = self._fresh_moments(params)
                        ckpt.save(
                            checkpoint_dir, epoch_i + 1,
                            {"params": params, "opt_state": save_opt},
                            history=dict(self.history),
                            async_save=checkpoint_async,
                        )
                        last_save = time.monotonic()
                    if verbose:
                        from learningorchestra_tpu.log import get_logger

                        get_logger("train").info(
                            "epoch %d/%d: %s", epoch_i + 1, epochs, metrics
                        )
                    if self.stop_training:
                        break

        finally:
            if checkpoint_dir:
                from learningorchestra_tpu.train import (
                    checkpoint as ckpt,
                )

                # The last async save must be durable when fit
                # returns — exception paths included.
                ckpt.finalize_async(checkpoint_dir)
        self._hand_back(params, opt_state)
        n_epochs = len(self.history.get("loss", ()))
        for i in range(n_epochs - ran, n_epochs):
            est.history.append(
                {k: v[i] for k, v in self.history.items() if len(v) > i}
            )
        return self

    def _fit_streaming(
        self, x, y, *, epochs, batch_size, validation_data, shuffle,
        verbose, checkpoint_dir, checkpoint_every,
        checkpoint_min_interval_s, resume, accumulate_steps,
        checkpoint_async: bool = True, callbacks: list | None = None,
    ) -> "DistributedTrainer":
        """Shard-streaming distributed fit over a beyond-RAM dataset.

        Per shard: host-side batching (fresh rng per (epoch, shard) —
        deterministic across processes, so every host computes the SAME
        batch composition, the multi-process invariant ``_put_global``
        relies on), global placement over the data axes, one resident-
        epoch call.  Shard k+1 loads and batches on an IO thread while
        the mesh runs shard k; ``_put_global`` stays on the caller
        thread (multi-controller collectives must issue in one order).
        Host memory peaks at O(shard), device memory at O(shard/dp) —
        the BASELINE config-5 shape (ResNet/ImageNet on a v4-32) that a
        whole-dataset upload can never satisfy.  Reference contract:
        database_api_image/database.py:86-151.
        """
        import concurrent.futures

        from learningorchestra_tpu.store import sharded as sh
        from learningorchestra_tpu.train.neural import _is_sharded

        if _is_sharded(validation_data):
            raise ValueError(
                "validation_data must be in-memory arrays, not sharded "
                "views"
            )
        x, y = sh.resolve_xy_views(x, y)

        est = self.estimator
        # Same column memory the single-device streaming fit records:
        # a later est.predict(bare_dataset) must select these features,
        # not the label column too.
        est._sharded_fit_cols = list(x.cols)
        est._set_accumulation(accumulate_steps)
        ds = x.dataset
        y_head = np.asarray(y.head(256))
        loss_kind = est._resolve_loss(y_head)
        y_cast = np.int32 if loss_kind == "softmax_ce" else np.float32
        if batch_size % self.data_axes:
            raise ValueError(
                f"global batch_size {batch_size} not divisible by "
                f"dp*fsdp={self.data_axes}"
            )
        self._check_seq_divisible(np.asarray(x.head(1)))

        def load(epoch_i: int, pos: int, k: int):
            # IO thread: disk → host arrays → host-side batching.  The
            # rng seeds on (epoch, shard position) so every process
            # computes identical batch composition.
            xs = x.load_shard(k)
            ys = y.load_shard(k).astype(y_cast)
            rng = (
                np.random.default_rng(
                    [est.seed, 7 + epoch_i, pos]
                ) if shuffle else _NoShuffle()
            )
            return _batch_data(xs, ys, batch_size, rng)

        start_epoch = 0
        try:
            with self._mesh_bound():
                if est.params is None:
                    est._init_params(
                        jnp.asarray(np.asarray(x.head(1), np.float32))
                    )
                self._ensure_fns(loss_kind, shuffle)
                params, opt_state = self._place_state()
                if checkpoint_dir and resume:
                    from learningorchestra_tpu.train import checkpoint as ckpt

                    loaded = ckpt.load_latest(
                        checkpoint_dir,
                        {"params": params, "opt_state": opt_state},
                    )
                    if loaded is not None:
                        state, step, past_history = loaded
                        params = state["params"]
                        opt_state = state["opt_state"]
                        self.history = TrainHistory(past_history)
                        start_epoch = step

                root_key = jax.random.PRNGKey(est.seed)
                last_save = time.monotonic()
                ran = 0  # epochs executed THIS call
                with concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="shard-io"
                ) as io:
                    for epoch_i in range(start_epoch, epochs):
                        if cancel_requested():
                            # Same contract as the in-memory loop.
                            self.stop_training = True
                            break
                        ran += 1
                        t0 = time.perf_counter()
                        # Same shard order on every process.
                        order = (
                            np.random.default_rng(
                                [est.seed, 3, epoch_i]
                            ).permutation(ds.n_shards)
                            if shuffle else np.arange(ds.n_shards)
                        )
                        acc = sh.WeightedMetrics()
                        nxt = io.submit(load, epoch_i, 0, int(order[0]))
                        for pos, k in enumerate(order):
                            xb, yb, mb = nxt.result()
                            if pos + 1 < len(order):
                                nxt = io.submit(
                                    load, epoch_i, pos + 1,
                                    int(order[pos + 1]),
                                )
                            tokens = np.issubdtype(xb.dtype, np.integer)
                            params, opt_state, metrics = self._epoch_fn(
                                params, opt_state,
                                self._put_global(
                                    xb, self._data_sharding(xb.ndim, tokens)
                                ),
                                self._put_global(
                                    yb, self._data_sharding(yb.ndim, False)
                                ),
                                self._put_global(
                                    mb, self._data_sharding(mb.ndim, False)
                                ),
                                jax.random.fold_in(
                                    root_key, epoch_i * ds.n_shards + pos
                                ),
                            )
                            acc.add(
                                jax.device_get(metrics),
                                ds.shard_rows[int(k)],
                            )
                        metrics = acc.result()
                        dt = time.perf_counter() - t0
                        metrics["epoch_time"] = dt
                        metrics["samples_per_sec"] = ds.n_rows / dt
                        if validation_data is not None:
                            vx, vy = validation_data
                            metrics.update({
                                f"val_{k2}": v
                                for k2, v in self.evaluate(
                                    vx, vy, batch_size=batch_size,
                                    _params=params,
                                ).items()
                            })
                        self.history.append(metrics)
                        # Same per-epoch span as the in-memory loop;
                        # ``streaming`` marks the sharded-dataset path.
                        obs_tracing.record_span(
                            "epoch", time.perf_counter() - t0,
                            epoch=epoch_i, distributed=True,
                            streaming=True,
                        )
                        from learningorchestra_tpu.train import (
                            checkpoint as ckpt,
                        )

                        if verbose:
                            from learningorchestra_tpu.log import get_logger

                            get_logger("train").info(
                                "epoch %d/%d: %s", epoch_i + 1, epochs,
                                metrics,
                            )
                        # Re-anchor so restore-best can snapshot/replace
                        # the sharded state (see the in-memory loop).
                        self.params, self.opt_state = params, opt_state
                        for cb in callbacks or []:
                            if callable(cb):
                                cb(epoch_i, metrics, self)
                        params, opt_state = self.params, self.opt_state
                        if opt_state is None and not self.stop_training:
                            opt_state = self._fresh_moments(params)
                            self.opt_state = opt_state
                        if checkpoint_dir and ckpt.should_save(
                            epoch_i, epochs, checkpoint_every,
                            checkpoint_min_interval_s, last_save,
                            stopped=self.stop_training,
                        ):
                            save_opt = opt_state
                            if save_opt is None:
                                # restore-best: restored params persist
                                # with fresh moments (single-device
                                # contract).
                                save_opt = self._fresh_moments(params)
                            ckpt.save(
                                checkpoint_dir, epoch_i + 1,
                                {"params": params,
                                 "opt_state": save_opt},
                                history=dict(self.history),
                                async_save=checkpoint_async,
                            )
                            last_save = time.monotonic()
                        if self.stop_training:
                            break

        finally:
            if checkpoint_dir:
                from learningorchestra_tpu.train import (
                    checkpoint as ckpt,
                )

                # Durable-on-return, exception paths included.
                ckpt.finalize_async(checkpoint_dir)
        self._hand_back(params, opt_state)
        n_epochs = len(self.history.get("loss", ()))
        for i in range(n_epochs - ran, n_epochs):
            est.history.append(
                {k: v[i] for k, v in self.history.items() if len(v) > i}
            )
        return self

    def evaluate(
        self, x, y, batch_size: int = 128, _params=None, **_
    ) -> dict:
        from learningorchestra_tpu.train.neural import _is_sharded

        if _is_sharded(x) or _is_sharded(y):
            # Shard-streaming evaluate — beyond-RAM datasets never
            # materialize on host (same contract as the single-device
            # surface, neural.py::_evaluate_streaming).
            from learningorchestra_tpu.store import sharded as sh

            x, y = sh.resolve_xy_views(x, y)
            acc = sh.WeightedMetrics()
            for k in range(x.dataset.n_shards):
                xs = x.load_shard(k)
                acc.add(
                    self.evaluate(
                        xs, y.load_shard(k), batch_size=batch_size,
                        _params=_params,
                    ),
                    len(xs),
                )
            return acc.result()
        est = self.estimator
        x = np.asarray(as_array(x))
        y_arr = np.asarray(y if not hasattr(y, "to_numpy") else y.to_numpy())
        if y_arr.ndim == 2 and y_arr.shape[1] == 1:
            y_arr = y_arr.reshape(-1)
        loss_kind = self._loss_kind or est._resolve_loss(y_arr)
        y_arr = y_arr.astype(
            np.int32 if loss_kind == "softmax_ce" else np.float32
        )
        self._check_seq_divisible(x)
        with self._mesh_bound():
            if self._eval_fn is None:
                self._ensure_fns(loss_kind, shuffle=False)
            params = _params if _params is not None else est.params
            # Round up to a shardable global batch instead of erroring —
            # eval batch size is a throughput knob, not a semantic one.
            batch_size = -(-max(1, batch_size) // self.data_axes) \
                * self.data_axes
            xb, yb, mb = _batch_data(x, y_arr, batch_size, _NoShuffle())
            tokens = np.issubdtype(x.dtype, np.integer)
            metrics = self._eval_fn(
                params,
                self._put_global(xb, self._data_sharding(xb.ndim, tokens)),
                self._put_global(yb, self._data_sharding(yb.ndim, False)),
                self._put_global(mb, self._data_sharding(mb.ndim, False)),
            )
            return {k: float(v) for k, v in metrics.items()}


def distributed_fit(
    estimator: NeuralEstimator,
    x,
    y,
    *,
    mesh_spec: dict | MeshSpec | None = None,
    **fit_kwargs,
) -> NeuralEstimator:
    """One-call distributed training — the executor-service entry point for
    the reference's ``POST /train/horovod`` route (SURVEY §2.2)."""
    if isinstance(mesh_spec, dict):
        mesh_spec = MeshSpec.from_dict(mesh_spec)
    trainer = DistributedTrainer(estimator, spec=mesh_spec)
    trainer.fit(x, y, **fit_kwargs)
    return estimator
