"""Multi-host control plane: coordinator + host agents.

Replaces the reference's Ray stack — Ray client (`ray://`, port 10001),
GCS (6379), placement groups with a 120 s timeout, and `runtime_env`
function shipping (reference: microservices/binary_executor_image/
server.py:13-17, start.sh:7, docker-compose.yml:329-347) — with the
framework's own minimal control plane:

- **data plane is NOT here.** Gradients/activations move as XLA
  collectives over ICI/DCN compiled into the jitted step (SURVEY §5.8);
  the control plane only carries job specs and status JSON.
- ``init_multihost`` bootstraps JAX's own multi-process runtime
  (``jax.distributed.initialize``) so every host joins one global device
  mesh — the TPU-pod analogue of workers joining the Gloo ring.
- ``Coordinator`` (HTTP, stdlib-only) tracks registered ``HostAgent``s,
  leases work, and records heartbeats; agents poll for jobs, run a
  registered callable, and report results.  Functions are *named registry
  entries*, never pickled code over the wire (the reference ships raw
  source and ``exec``s it — binary_execution.py:328-348).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger, kv

logger = get_logger("coordinator")

DEFAULT_PLACEMENT_TIMEOUT_S = 120.0  # reference parity: server.py:16
HEARTBEAT_INTERVAL_S = 5.0
AGENT_DEAD_AFTER_S = 30.0


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join this host to the global JAX runtime (ICI within a slice, DCN
    across slices).  Arguments default from env so a launcher can export
    ``LO_COORDINATOR``/``LO_NUM_PROCESSES``/``LO_PROCESS_ID`` and run the
    same command on every host."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "LO_COORDINATOR"
    )
    if coordinator_address is None:
        return  # single-host: nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes
        or int(os.environ["LO_NUM_PROCESSES"]),
        process_id=process_id
        if process_id is not None
        else int(os.environ["LO_PROCESS_ID"]),
    )


# -- function registry (the anti-`exec` boundary) ---------------------------

_functions: dict[str, Callable] = {}
_functions_lock = make_lock("coordinator._functions_lock")


def register_function(name: str, fn: Callable | None = None):
    """Register a callable agents may run. Usable as a decorator."""

    def deco(f):
        with _functions_lock:
            _functions[name] = f
        return f

    return deco(fn) if fn is not None else deco


def get_function(name: str) -> Callable:
    with _functions_lock:
        fn = _functions.get(name)
    if fn is None:
        raise KeyError(f"no registered distributed function {name!r}")
    return fn


# -- coordinator ------------------------------------------------------------


class Coordinator:
    """Cluster-side registry + job queue, served over HTTP (stdlib only).

    Endpoints (all JSON):
      POST /agents/register   {agent_id, capacity}    → {ok}
      POST /agents/heartbeat  {agent_id}              → {ok}
      GET  /agents                                    → {agents: {...}}
      POST /jobs              {function, kwargs, n_agents?} → {job_id}
      GET  /jobs/{id}                                 → job record
      POST /jobs/{id}/lease   {agent_id}              → {task} | 204
      POST /jobs/{id}/result  {agent_id, result|error} → {ok}
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = make_lock("Coordinator._lock")
        self._agents: dict[str, dict] = {}
        self._jobs: dict[str, dict] = {}
        self._next_job = 0
        # Boot-scoped ID namespace: a restarted coordinator must never
        # recycle a previous boot's job IDs — a client tolerating a
        # transient outage (wait_job's unreachable grace) could latch
        # onto a DIFFERENT submitter's recycled "job-0" and record the
        # wrong job's results as its own.  With the boot token, a lost
        # job's ID can only ever answer 404.
        self._boot = os.urandom(4).hex()
        coord = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, payload: dict | None):
                body = json.dumps(payload or {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self):
                try:
                    code, payload = coord._route(
                        "POST", self.path, self._body()
                    )
                except Exception as exc:  # noqa: BLE001
                    code, payload = 500, {"error": repr(exc)}
                self._json(code, payload)

            def do_GET(self):
                try:
                    code, payload = coord._route("GET", self.path, {})
                except Exception as exc:  # noqa: BLE001
                    code, payload = 500, {"error": repr(exc)}
                self._json(code, payload)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.address = (
            f"{host}:{self._server.server_address[1]}"
        )
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    # route dispatch -------------------------------------------------------

    def _route(self, verb: str, path: str, body: dict):
        parts = [p for p in path.split("/") if p]
        if verb == "POST" and parts == ["agents", "register"]:
            return 200, self.register_agent(
                body["agent_id"], int(body.get("capacity", 1))
            )
        if verb == "POST" and parts == ["agents", "heartbeat"]:
            return 200, self.heartbeat(body["agent_id"])
        if verb == "GET" and parts == ["agents"]:
            return 200, {"agents": self.agents()}
        if verb == "POST" and parts == ["jobs"]:
            return 201, {
                "job_id": self.submit(
                    body["function"],
                    body.get("kwargs", {}),
                    int(body.get("n_agents", 1)),
                )
            }
        if verb == "GET" and parts == ["jobs"]:
            return 200, {"queued": self.open_jobs()}
        if verb == "GET" and len(parts) == 2 and parts[0] == "jobs":
            job = self.job(parts[1])
            return (200, job) if job else (404, {"error": "no such job"})
        if (
            verb == "POST"
            and len(parts) == 3
            and parts[0] == "jobs"
            and parts[2] == "lease"
        ):
            task = self.lease(parts[1], body["agent_id"])
            return (200, {"task": task}) if task else (204, {})
        if (
            verb == "POST"
            and len(parts) == 3
            and parts[0] == "jobs"
            and parts[2] == "result"
        ):
            return 200, self.report(
                parts[1],
                body["agent_id"],
                body.get("result"),
                body.get("error"),
            )
        if (
            verb == "POST"
            and len(parts) == 3
            and parts[0] == "jobs"
            and parts[2] == "cancel"
        ):
            return 200, self.cancel(parts[1])
        if (
            verb == "POST"
            and len(parts) == 3
            and parts[0] == "jobs"
            and parts[2] == "rendezvous"
        ):
            return 200, self.set_rendezvous(parts[1], body["address"])
        return 404, {"error": f"no route {verb} {path}"}

    # core ops -------------------------------------------------------------

    def register_agent(self, agent_id: str, capacity: int = 1) -> dict:
        with self._lock:
            self._agents[agent_id] = {
                "capacity": capacity,
                "last_seen": time.time(),
            }
        logger.info(kv(event="agent_register", agent=agent_id,
                       capacity=capacity))
        return {"ok": True}

    def heartbeat(self, agent_id: str) -> dict:
        with self._lock:
            if agent_id in self._agents:
                self._agents[agent_id]["last_seen"] = time.time()
                return {"ok": True}
        # Unknown agent: the registry is in-memory, so this means the
        # coordinator RESTARTED since the agent registered (the Swarm
        # restart-policy path, reference: docker-compose.yml:3-6).
        # Tell the agent so it re-registers — silently answering ok
        # would leave the cluster looking empty forever.
        return {"ok": False, "unknown_agent": True}

    def agents(self) -> dict:
        now = time.time()
        with self._lock:
            return {
                aid: {**rec, "alive": now - rec["last_seen"]
                      < AGENT_DEAD_AFTER_S}
                for aid, rec in self._agents.items()
            }

    def submit(
        self, function: str, kwargs: dict, n_agents: int = 1
    ) -> str:
        with self._lock:
            job_id = f"job-{self._boot}-{self._next_job}"
            self._next_job += 1
            self._jobs[job_id] = {
                "job_id": job_id,
                "function": function,
                "kwargs": kwargs,
                "n_agents": n_agents,
                "leased": [],
                "ranks": {},  # agent_id → rank, stable across reclaims
                "results": {},
                "errors": {},
                "rendezvous": None,
                "state": "queued",
                "submitted": time.time(),
            }
        return job_id

    def open_jobs(self) -> list[str]:
        """Jobs still needing agents (queued or under-leased)."""
        with self._lock:
            return [
                jid
                for jid, job in self._jobs.items()
                if len(job["leased"]) < job["n_agents"]
            ]

    def job(self, job_id: str) -> dict | None:
        with self._lock:
            job = self._jobs.get(job_id)
            return dict(job) if job else None

    def set_rendezvous(self, job_id: str, address: str) -> dict:
        """Rank 0 publishes its ``jax.distributed`` rendezvous address
        here; the other ranks poll the job record for it — no static
        rank-0 host needs to be configured anywhere."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return {"ok": False, "error": f"unknown job {job_id}"}
            job["rendezvous"] = address
        return {"ok": True}

    def lease(self, job_id: str, agent_id: str) -> dict | None:
        now = time.time()
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            if job["state"] in ("cancelled", "finished", "failed"):
                # Terminal: no new leases, and never flip the state back
                # to running (cancel()'s guarantee).
                return None
            # Reclaim leases held by agents that stopped heartbeating and
            # never reported — the preemption-as-first-class-retry path
            # the reference lacks (SURVEY §5.3: a dead worker's job was
            # simply lost).
            for holder in list(job["leased"]):
                rec = self._agents.get(holder)
                dead = rec is None or (
                    now - rec["last_seen"] > AGENT_DEAD_AFTER_S
                )
                hrank = job["ranks"].get(holder)
                reported = hrank is not None and (
                    hrank in job["results"] or hrank in job["errors"]
                )
                if dead and not reported:
                    job["leased"].remove(holder)
                    job["ranks"].pop(holder, None)
                    logger.warning(kv(
                        event="lease_reclaimed", job=job_id,
                        dead_agent=holder, rank=hrank,
                    ))
            if len(job["leased"]) >= job["n_agents"]:
                return None
            if agent_id in job["leased"]:
                return None
            # Lowest free rank — a reclaimed lease re-issues the dead
            # agent's rank so the data partition is covered exactly once.
            taken = set(job["ranks"].values())
            rank = next(
                r for r in range(job["n_agents"]) if r not in taken
            )
            job["leased"].append(agent_id)
            job["ranks"][agent_id] = rank
            job["state"] = "running"
            return {
                "function": job["function"],
                "kwargs": job["kwargs"],
                "rank": rank,
                "world_size": job["n_agents"],
                "job_id": job_id,
            }

    def report(
        self, job_id: str, agent_id: str, result, error
    ) -> dict:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                # Stale report (e.g. coordinator restarted): acknowledge
                # without retry-able failure, nothing to record it against.
                return {"ok": False, "error": f"unknown job {job_id}"}
            # Results are keyed by RANK, not agent: completion means every
            # data partition 0..n-1 is covered exactly once, even when a
            # reclaimed lease re-issued a rank to a second agent.
            rank = job["ranks"].get(agent_id)
            if rank is None:
                # Lease was reclaimed (agent went dead, rank re-issued);
                # its partition is another agent's responsibility now.
                return {"ok": False, "error": "stale lease"}
            if error is not None:
                if rank not in job["results"]:
                    job["errors"][rank] = error
            else:
                job["results"][rank] = result
                job["errors"].pop(rank, None)
            covered = set(job["results"]) | set(job["errors"])
            if job["state"] != "cancelled" and len(covered) >= job[
                "n_agents"
            ]:
                job["state"] = "failed" if job["errors"] else "finished"
                logger.info(kv(
                    event="job_done", job=job_id, state=job["state"],
                    errors=len(job["errors"]),
                ))
        return {"ok": True}

    def cancel(self, job_id: str) -> dict:
        """Mark a job cancelled: no new leases are granted and late
        reports can no longer flip it to finished — running agents
        cannot be aborted mid-task (document for callers), but the
        caller knows the recorded outcome is final."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return {"ok": False, "error": f"unknown job {job_id}"}
            if job["state"] not in ("finished", "failed"):
                job["state"] = "cancelled"
                job["n_agents"] = len(job["leased"])  # stop new leases
        logger.warning(kv(event="job_cancelled", job=job_id))
        return {"ok": True}

    def wait(
        self, job_id: str, timeout: float = DEFAULT_PLACEMENT_TIMEOUT_S
    ) -> dict:
        """Block until the job finishes/fails — reference parity with the
        120 s Ray placement timeout."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = self.job(job_id)
            if job and job["state"] in (
                "finished", "failed", "cancelled"
            ):
                return job
            time.sleep(0.05)
        raise TimeoutError(f"job {job_id} timed out after {timeout}s")

    def start(self) -> "Coordinator":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# -- host agent -------------------------------------------------------------


def http_json(url: str, payload: dict | None = None,
              timeout: float = 10) -> tuple[int, dict]:
    """POST (payload given) or GET a JSON endpoint — the one client
    helper the agents AND the REST service's cluster dispatch share."""
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"},
        method="POST" if data is not None else "GET",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read()
        return resp.status, json.loads(body) if body else {}


_http = http_json  # internal alias, kept for call-site brevity


def submit_job(address: str, function: str, kwargs: dict,
               n_agents: int = 1) -> str:
    """Client-side submit against a remote coordinator."""
    status, payload = http_json(
        f"http://{address}/jobs",
        {"function": function, "kwargs": kwargs, "n_agents": n_agents},
    )
    if status != 201 or "job_id" not in payload:
        raise RuntimeError(
            f"coordinator rejected job submit ({status}): {payload}"
        )
    return payload["job_id"]


def wait_job(address: str, job_id: str, timeout: float,
             poll_interval: float = 1.0,
             unreachable_grace: float = 30.0) -> dict:
    """Client-side wait: poll until the job reaches a terminal state.
    On timeout the job is CANCELLED server-side before raising, so a
    late-finishing agent cannot silently flip the recorded outcome.

    Coordinator-death semantics (the Swarm restart-policy path): a
    connection-level failure is tolerated for ``unreachable_grace``
    seconds — a supervised restart must not kill a healthy fit the
    instant the socket blips — but a coordinator that answers 404 has
    RESTARTED AND LOST the in-memory job record: the fit fails
    immediately with a clean, named error (never a silent hang until
    the day-long job timeout), which lands it in the engine's
    failure ledger for a PATCH re-run.
    """
    import http.client
    import urllib.error

    deadline = time.time() + timeout
    last_ok = time.time()
    while time.time() < deadline:
        try:
            _, job = http_json(f"http://{address}/jobs/{job_id}")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise RuntimeError(
                    f"coordinator no longer knows job {job_id} — it "
                    "likely restarted and lost in-memory job state; "
                    "the fit is recorded failed (re-run via PATCH)"
                ) from exc
            raise
        except (OSError, http.client.HTTPException, ValueError) as exc:
            # OSError: refused/reset.  HTTPException/ValueError: the
            # coordinator died MID-RESPONSE (truncated body, half a
            # JSON document) — the same restart blip, same grace.
            if time.time() - last_ok > unreachable_grace:
                raise RuntimeError(
                    f"coordinator {address} unreachable for over "
                    f"{unreachable_grace:.0f}s while waiting on "
                    f"{job_id}: {exc}"
                ) from exc
            time.sleep(poll_interval)
            continue
        last_ok = time.time()
        if job.get("state") in ("finished", "failed", "cancelled"):
            return job
        time.sleep(poll_interval)
    try:
        http_json(f"http://{address}/jobs/{job_id}/cancel", {})
    except OSError:
        pass
    raise TimeoutError(f"job {job_id} timed out after {timeout}s")


class HostAgent:
    """Per-host worker: registers, heartbeats, leases tasks, runs
    registry functions, reports results.  The function gets
    ``rank``/``world_size`` kwargs — the ``hvd.rank()`` analogue
    (reference: train_function.py:55-61) without a Horovod runtime."""

    def __init__(self, coordinator_address: str, agent_id: str,
                 capacity: int = 1):
        self.base = f"http://{coordinator_address}"
        self.agent_id = agent_id
        self.capacity = capacity
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self) -> None:
        _http(
            f"{self.base}/agents/register",
            {"agent_id": self.agent_id, "capacity": self.capacity},
        )

    def run_job(self, job_id: str) -> bool:
        """Try to lease + run one task of ``job_id``; True if ran."""
        status, payload = _http(
            f"{self.base}/jobs/{job_id}/lease", {"agent_id": self.agent_id}
        )
        if status != 200 or not payload.get("task"):
            return False
        task = payload["task"]
        try:
            fn = get_function(task["function"])
            kwargs = dict(task["kwargs"])
            # Functions that declare job_meta get the coordinator
            # back-channel (rendezvous publication etc.).
            import inspect

            if "job_meta" in inspect.signature(fn).parameters:
                kwargs["job_meta"] = {
                    "job_id": task.get("job_id"),
                    "coordinator": self.base,
                }
            result = fn(
                rank=task["rank"],
                world_size=task["world_size"],
                **kwargs,
            )
            report = {"agent_id": self.agent_id, "result": result}
        except Exception as exc:  # noqa: BLE001 — ledger contract §5.3
            report = {"agent_id": self.agent_id, "error": repr(exc)}
        # Report delivery is retried separately from task execution: a
        # transient POST failure must not turn a successful run into a
        # recorded task failure.
        for attempt in range(3):
            try:
                _http(f"{self.base}/jobs/{job_id}/result", report)
                break
            except OSError:
                if attempt == 2:
                    raise
                time.sleep(0.2 * (attempt + 1))
        return True

    def serve(self, poll_interval: float = 0.05) -> None:
        """Background loop: heartbeat + lease any queued/running job."""
        self.register()

        def loop():
            last_beat = 0.0
            while not self._stop.is_set():
                now = time.time()
                if now - last_beat > HEARTBEAT_INTERVAL_S:
                    try:
                        _, beat = _http(
                            f"{self.base}/agents/heartbeat",
                            {"agent_id": self.agent_id},
                        )
                        if beat.get("unknown_agent"):
                            # Coordinator restarted with an empty
                            # registry: rejoin so new jobs can be
                            # placed on this host again.
                            logger.info(kv(
                                event="agent_reregister",
                                agent=self.agent_id,
                            ))
                            self.register()
                    except OSError:
                        pass
                    last_beat = now
                # Lease scan by polling: keeps the agent dependency-free
                # and tolerant of coordinator restarts (push would need a
                # persistent channel).
                for job_id in self._visible_jobs():
                    try:
                        self.run_job(job_id)
                    except OSError:
                        break  # coordinator unreachable; retry next tick
                self._stop.wait(poll_interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def _visible_jobs(self) -> list[str]:
        try:
            _, payload = _http(f"{self.base}/jobs")
        except (OSError, ValueError):
            return []
        return payload.get("queued", [])

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
