"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

The reference has no attention code at all and its sequence length is
bounded by one worker's ``model.fit`` memory (SURVEY §5.7).  This module
is the long-context capability the TPU framework adds: the sequence axis
is sharded across devices, each device holds one query block resident,
and key/value blocks rotate around the ring via ``lax.ppermute`` — one
ICI hop per step, overlapping the blockwise attention compute.  Softmax
is computed online (running max / running sum), so the result is *exact*
attention, never materializing the (T, T) score matrix on any device.

Memory per device: O(T/sp · d) activations + O((T/sp)²) scores — a
T=128k sequence on sp=16 attends with 8k-block arithmetic.

Pattern follows the public blockwise/ring-attention recipe (Liu et al.,
ring attention; flash-style online softmax) as described in PAPERS.md —
implementation is original and JAX-idiomatic: ``shard_map`` for the
manual-collective region, ``lax.fori_loop`` with static trip count so the
whole ring unrolls into one compiled loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, kmask, bias):
    """Scores for one (q-block, k-block) pair.

    q: (B, Tq, H, D)   k/v: (B, Tk, H, D)   kmask: (B, Tk) or None
    bias: (Tq, Tk) additive or None.  Returns (scores (B,H,Tq,Tk), v).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias[None, None, :, :]
    if kmask is not None:
        s = jnp.where(kmask[:, None, None, :], s, NEG_INF)
    return s


def _online_update(carry_o, carry_m, carry_l, s, v):
    """Fold one block of scores into the running softmax accumulators."""
    m_new = jnp.maximum(carry_m, s.max(axis=-1))
    corr = jnp.exp(carry_m - m_new)
    p = jnp.exp(s - m_new[..., None])  # (B, H, Tq, Tk)
    l_new = carry_l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = carry_o * corr[..., None].transpose(0, 2, 1, 3) + pv
    return o_new, m_new, l_new


def _ring_attention_sharded(
    q, k, v, kmask, axis_name: str, causal: bool, mesh_axes: tuple
):
    """Per-shard body (runs under shard_map): full ring of K/V rotations.

    Shapes per device: q,k,v (B, T_local, H, D); kmask (B, T_local).
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Accumulators start as constants but become device-varying once the
    # rotating K/V blocks fold in — cast them varying up front so the
    # fori_loop carry types match under shard_map's vma check.
    def _varying(x):
        return jax.lax.pcast(x, mesh_axes, to="varying")

    o0 = _varying(jnp.zeros((b, t_loc, h, d), jnp.float32))
    m0 = _varying(jnp.full((b, h, t_loc), NEG_INF, jnp.float32))
    l0 = _varying(jnp.zeros((b, h, t_loc), jnp.float32))

    q32 = q.astype(jnp.float32)

    def body(step, state):
        o, m, l, kb, vb, km = state
        # kb originated on device (my_idx - step) mod axis_size.
        src = (my_idx - step) % axis_size
        if causal:
            q_pos = my_idx * t_loc + jnp.arange(t_loc)
            k_pos = src * t_loc + jnp.arange(t_loc)
            bias = jnp.where(
                q_pos[:, None] >= k_pos[None, :], 0.0, NEG_INF
            )
        else:
            bias = None
        s = _block_attend(q32, kb.astype(jnp.float32),
                          vb.astype(jnp.float32), km, bias)
        o, m, l = _online_update(o, m, l, s, vb.astype(jnp.float32))
        # Rotate K/V (and the key-padding mask) one hop around the ring.
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        if km is not None:
            km = jax.lax.ppermute(km, axis_name, perm)
        return o, m, l, kb, vb, km

    o, m, l, *_ = jax.lax.fori_loop(
        0, axis_size, body, (o0, m0, l0, k, v, kmask)
    )
    # (B, H, Tq) -> (B, Tq, H, 1) for the normalizer.
    l = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / l).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    *,
    mesh: Mesh,
    kmask=None,
    axis_name: str = "sp",
    causal: bool = False,
    batch_axes: tuple = ("dp", "fsdp"),
    head_axis: str | None = "tp",
):
    """Exact multi-head attention with the sequence axis sharded on
    ``axis_name``.  Inputs are GLOBAL arrays (B, T, H, D) — under jit
    they may already be sharded; shard_map re-annotates.

    ``kmask`` (B, T) marks valid key positions (pad id masking).
    """
    ha = head_axis if head_axis and mesh.shape.get(head_axis, 1) > 1 else None
    qkv_spec = P(batch_axes, axis_name, ha, None)
    mask_spec = P(batch_axes, axis_name)
    varying = tuple(batch_axes) + (axis_name,) + ((ha,) if ha else ())
    body = functools.partial(
        _ring_attention_sharded,
        axis_name=axis_name,
        causal=causal,
        mesh_axes=varying,
    )
    if kmask is None:
        fn = jax.shard_map(
            lambda q, k, v: body(q, k, v, None),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
        )
        return fn(q, k, v)
    fn = jax.shard_map(
        lambda q, k, v, km: body(q, k, v, km),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, kmask)


def reference_attention(q, k, v, kmask=None, causal: bool = False):
    """Unsharded exact attention — the correctness oracle for tests."""
    s = _block_attend(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), kmask, None
    )
    if causal:
        t = q.shape[1]
        bias = jnp.where(
            jnp.arange(t)[:, None] >= jnp.arange(t)[None, :], 0.0, NEG_INF
        )
        s = s + bias[None, None]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


class RingSelfAttention(nn.Module):
    """Drop-in Flax self-attention block that runs ring attention when a
    mesh with sp>1 is supplied, falling back to vanilla attention.

    Used by the long-context transformer (models/longcontext.py); QKV/out
    projections are plain Dense layers, so they pick up tp sharding from
    the standard partition rules (parallel/sharding.py).
    """

    num_heads: int
    mesh: Mesh | None = None
    causal: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, kmask=None):
        b, t, hidden = x.shape
        head_dim = hidden // self.num_heads
        qkv = nn.DenseGeneral(
            (3, self.num_heads, head_dim), dtype=self.dtype, name="qkv"
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.mesh is not None and self.mesh.shape.get("sp", 1) > 1:
            o = ring_attention(
                q, k, v, mesh=self.mesh, kmask=kmask, causal=self.causal
            )
        else:
            o = reference_attention(
                q, k, v, kmask=kmask, causal=self.causal
            ).astype(self.dtype)
        o = o.reshape(b, t, hidden)
        return nn.Dense(hidden, dtype=self.dtype, name="out")(o)
