"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

The reference has no attention code at all and its sequence length is
bounded by one worker's ``model.fit`` memory (SURVEY §5.7).  This module
is the long-context capability the TPU framework adds: the sequence axis
is sharded across devices, each device holds one query block resident,
and key/value blocks rotate around the ring via ``lax.ppermute`` — one
ICI hop per step, overlapping the blockwise attention compute.  Softmax
is computed online (running max / running sum), so the result is *exact*
attention, never materializing the (T, T) score matrix on any device.

Memory per device: O(T/sp · d) activations + O((T/sp)²) scores — a
T=128k sequence on sp=16 attends with 8k-block arithmetic.

Pattern follows the public blockwise/ring-attention recipe (Liu et al.,
ring attention; flash-style online softmax) as described in PAPERS.md —
implementation is original and JAX-idiomatic: ``shard_map`` for the
manual-collective region, ``lax.fori_loop`` with static trip count so the
whole ring unrolls into one compiled loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, kmask, bias):
    """Scores for one (q-block, k-block) pair.

    q: (B, Tq, H, D)   k/v: (B, Tk, H, D)   kmask: (B, Tk) or None
    bias: (Tq, Tk) additive or None.  Returns (scores (B,H,Tq,Tk), v).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias[None, None, :, :]
    if kmask is not None:
        s = jnp.where(kmask[:, None, None, :], s, NEG_INF)
    return s


def _online_update(carry_o, carry_m, carry_l, s, v):
    """Fold one block of scores into the running softmax accumulators."""
    m_new = jnp.maximum(carry_m, s.max(axis=-1))
    corr = jnp.exp(carry_m - m_new)
    p = jnp.exp(s - m_new[..., None])  # (B, H, Tq, Tk)
    l_new = carry_l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = carry_o * corr[..., None].transpose(0, 2, 1, 3) + pv
    return o_new, m_new, l_new


def _ring_attention_sharded(
    q, k, v, kmask, axis_name: str, causal: bool, mesh_axes: tuple
):
    """Per-shard body (runs under shard_map): full ring of K/V rotations.

    Shapes per device: q,k,v (B, T_local, H, D); kmask (B, T_local).
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Accumulators start as constants but become device-varying once the
    # rotating K/V blocks fold in — cast them varying up front so the
    # fori_loop carry types match under shard_map's vma check.
    def _varying(x):
        return jax.lax.pcast(x, mesh_axes, to="varying")

    o0 = _varying(jnp.zeros((b, t_loc, h, d), jnp.float32))
    m0 = _varying(jnp.full((b, h, t_loc), NEG_INF, jnp.float32))
    l0 = _varying(jnp.zeros((b, h, t_loc), jnp.float32))

    q32 = q.astype(jnp.float32)

    def body(step, state):
        o, m, l, kb, vb, km = state
        # kb originated on device (my_idx - step) mod axis_size.
        src = (my_idx - step) % axis_size
        if causal:
            q_pos = my_idx * t_loc + jnp.arange(t_loc)
            k_pos = src * t_loc + jnp.arange(t_loc)
            bias = jnp.where(
                q_pos[:, None] >= k_pos[None, :], 0.0, NEG_INF
            )
        else:
            bias = None
        s = _block_attend(q32, kb.astype(jnp.float32),
                          vb.astype(jnp.float32), km, bias)
        o, m, l = _online_update(o, m, l, s, vb.astype(jnp.float32))
        # Rotate K/V (and the key-padding mask) one hop around the ring.
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        if km is not None:
            km = jax.lax.ppermute(km, axis_name, perm)
        return o, m, l, kb, vb, km

    o, m, l, *_ = jax.lax.fori_loop(
        0, axis_size, body, (o0, m0, l0, k, v, kmask)
    )
    # (B, H, Tq) -> (B, Tq, H, 1) for the normalizer.
    l = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / l).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    *,
    mesh: Mesh,
    kmask=None,
    axis_name: str = "sp",
    causal: bool = False,
    batch_axes: tuple = ("dp", "fsdp"),
    head_axis: str | None = "tp",
):
    """Exact multi-head attention with the sequence axis sharded on
    ``axis_name``.  Inputs are GLOBAL arrays (B, T, H, D) — under jit
    they may already be sharded; shard_map re-annotates.

    ``kmask`` (B, T) marks valid key positions (pad id masking).
    """
    ha = head_axis if head_axis and mesh.shape.get(head_axis, 1) > 1 else None
    qkv_spec = P(batch_axes, axis_name, ha, None)
    mask_spec = P(batch_axes, axis_name)
    varying = tuple(batch_axes) + (axis_name,) + ((ha,) if ha else ())
    body = functools.partial(
        _ring_attention_sharded,
        axis_name=axis_name,
        causal=causal,
        mesh_axes=varying,
    )
    if kmask is None:
        fn = jax.shard_map(
            lambda q, k, v: body(q, k, v, None),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
        )
        return fn(q, k, v)
    fn = jax.shard_map(
        lambda q, k, v, km: body(q, k, v, km),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, kmask)


# ---------------------------------------------------------------------------
# Ring-flash: the Pallas flash kernel inside each ring step
# ---------------------------------------------------------------------------
#
# The jnp ring above materializes (T/sp)² f32 scores per device per step.
# Ring-flash replaces the per-step block attention with the streamed
# Pallas kernel (ops/attention.py): per-device memory falls to O(block)
# and the matmuls run bf16 on the MXU.  The backward is a hand-written
# reverse ring (custom_vjp): dq accumulates locally while dk/dv partials
# rotate WITH their K/V blocks and arrive home after a full circuit —
# the ring-flash recipe from PAPERS.md, built on this repo's kernels.

_MERGE_EMPTY = -1e30  # merge-domain lse for "no keys seen yet"


def _kernel_lse_to_merge(lse):
    """Kernel sentinel (+1e30 for fully-masked rows) -> merge domain."""
    return jnp.where(lse > 1e29, _MERGE_EMPTY, lse)


def _merge_partials(o_c, lse_c, o_b, lse_b):
    """Fold one block's normalized output into the running result.

    Both sides carry softmax-NORMALIZED outputs plus their lse; the
    exact combination re-weights by exp(lse - m) with empty sides
    contributing weight 0.
    """
    m = jnp.maximum(lse_c, lse_b)
    wc = jnp.where(lse_c > _MERGE_EMPTY / 2, jnp.exp(lse_c - m), 0.0)
    wb = jnp.where(lse_b > _MERGE_EMPTY / 2, jnp.exp(lse_b - m), 0.0)
    denom = wc + wb
    safe = jnp.where(denom > 0.0, denom, 1.0)
    o = (o_c * wc + o_b * wb) / safe
    lse = jnp.where(
        denom > 0.0, m + jnp.log(safe), _MERGE_EMPTY
    )
    return o, lse


def _ring_blocks(t_loc: int, block_q: int | None, block_k: int | None
                 ) -> tuple[int, int, int]:
    """(block_q, block_k, pad) for the local length.

    Starts from flash_attention's length-adaptive defaults, clamps to
    the local length, then forces the smaller block to divide the
    larger so ONE pad amount makes the padded length divisible by both
    — otherwise a t_loc between the two block sizes (e.g. 384 with
    blocks 256/512) would leave trailing query rows outside the kernel
    grid entirely.
    """
    bq = block_q or (256 if t_loc <= 8192 else 512)
    bk = block_k or (512 if t_loc <= 8192 else 1024)
    bq = min(bq, max(8, t_loc))
    bk = min(bk, max(8, t_loc))
    if bk >= bq:
        bk -= bk % bq
    else:
        bq -= bq % bk
    pad = (-t_loc) % max(bq, bk)
    return bq, bk, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ring_flash_core(q, k, v, km, opts):
    out, _ = _ring_flash_fwd(q, k, v, km, opts)
    return out


def _ring_steps(opts):
    axis, causal, bq, bk, interpret = opts
    n = jax.lax.psum(1, axis)  # mesh axis size: a static int
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return axis, causal, bq, bk, interpret, n, me, perm


def _step_branch(causal, me, src, n):
    """0 = full block, 1 = diagonal (causal within), 2 = skip (future)."""
    if not causal:
        return jnp.int32(0)
    return jnp.where(src == me, 1, jnp.where(src < me, 0, 2))


def _ring_flash_fwd(q, k, v, km, opts):
    from learningorchestra_tpu.ops.attention import _fwd_call

    axis, causal, bq, bk, interpret, n, me, perm = _ring_steps(opts)
    b, h, t, d = q.shape

    def call(kb, vb, kmb, diag):
        o, lse = _fwd_call(q, kb, vb, kmb, bq, bk, interpret, diag)
        return o.astype(jnp.float32), _kernel_lse_to_merge(lse)

    def skip(kb, vb, kmb):
        return (
            jnp.zeros((b, h, t, d), jnp.float32),
            jnp.full((b, h, t, 1), _MERGE_EMPTY, jnp.float32),
        )

    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    l0 = jnp.full((b, h, t, 1), _MERGE_EMPTY, jnp.float32)

    def body(step, state):
        o, lse, kb, vb, kmb = state
        src = (me - step) % n
        ob, lseb = jax.lax.switch(
            _step_branch(causal, me, src, n),
            [
                lambda kb, vb, kmb: call(kb, vb, kmb, False),
                lambda kb, vb, kmb: call(kb, vb, kmb, True),
                skip,
            ],
            kb, vb, kmb,
        )
        o, lse = _merge_partials(o, lse, ob, lseb)
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        kmb = jax.lax.ppermute(kmb, axis, perm)
        return o, lse, kb, vb, kmb

    o, lse, *_ = jax.lax.fori_loop(0, n, body, (o0, l0, k, v, km))
    out = o.astype(q.dtype)
    # Back to the kernel's sentinel domain for the backward pass.
    lse_s = jnp.where(lse <= _MERGE_EMPTY / 2, 1e30, lse)
    return out, lse_s


def _ring_flash_core_fwd(q, k, v, km, opts):
    out, lse = _ring_flash_fwd(q, k, v, km, opts)
    return out, (q, k, v, km, out, lse)


def _ring_flash_core_bwd(opts, res, g):
    from learningorchestra_tpu.ops.attention import _bwd_call

    axis, causal, bq, bk, interpret, n, me, perm = _ring_steps(opts)
    q, k, v, km, o, lse = res
    do32 = g.astype(jnp.float32)
    delta = jnp.sum(
        do32 * o.astype(jnp.float32), axis=-1, keepdims=True
    )
    do = do32.astype(q.dtype)

    def call(kb, vb, kmb, diag):
        dq, dk, dv = _bwd_call(
            q, kb, vb, kmb, do, lse, delta, bq, bk, interpret, diag
        )
        return (
            dq.astype(jnp.float32),
            dk.astype(jnp.float32),
            dv.astype(jnp.float32),
        )

    def skip(kb, vb, kmb):
        z = jnp.zeros(q.shape, jnp.float32)
        return z, jnp.zeros(k.shape, jnp.float32), \
            jnp.zeros(v.shape, jnp.float32)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)

    def body(step, state):
        dq, kb, vb, kmb, dkb, dvb = state
        src = (me - step) % n
        dqs, dks, dvs = jax.lax.switch(
            _step_branch(causal, me, src, n),
            [
                lambda kb, vb, kmb: call(kb, vb, kmb, False),
                lambda kb, vb, kmb: call(kb, vb, kmb, True),
                skip,
            ],
            kb, vb, kmb,
        )
        dq = dq + dqs
        dkb = dkb + dks
        dvb = dvb + dvs
        # dk/dv partials travel WITH their block; after the full
        # circuit each block (and its gradient) is home.
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        kmb = jax.lax.ppermute(kmb, axis, perm)
        dkb = jax.lax.ppermute(dkb, axis, perm)
        dvb = jax.lax.ppermute(dvb, axis, perm)
        return dq, kb, vb, kmb, dkb, dvb

    dq, _, _, _, dk, dv = jax.lax.fori_loop(
        0, n, body, (dq0, k, v, km, dk0, dv0)
    )
    return (
        dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
        jnp.zeros_like(km),
    )


_ring_flash_core.defvjp(_ring_flash_core_fwd, _ring_flash_core_bwd)


def ring_flash_attention(
    q,
    k,
    v,
    *,
    mesh: Mesh,
    kmask=None,
    axis_name: str = "sp",
    causal: bool = False,
    batch_axes: tuple = ("dp", "fsdp"),
    head_axis: str | None = "tp",
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Ring attention with the Pallas flash kernel per step.

    Same contract as :func:`ring_attention` (global (B, T, H, D)
    arrays, sequence sharded over ``axis_name``), but per-device memory
    is O(kernel block) instead of O((T/sp)²) and the block matmuls run
    in storage dtype on the MXU.  Off-TPU the kernels run in interpret
    mode — tests only; use :func:`ring_attention` for real CPU work.
    """
    from learningorchestra_tpu.ops.attention import _auto_interpret

    if interpret is None:
        interpret = _auto_interpret()
    ha = head_axis if head_axis and mesh.shape.get(head_axis, 1) > 1 else None
    qkv_spec = P(batch_axes, axis_name, ha, None)
    mask_spec = P(batch_axes, axis_name)
    b, t, h_, d = q.shape
    sp = mesh.shape.get(axis_name, 1)
    if t % sp:
        raise ValueError(f"sequence {t} not divisible by {axis_name}={sp}")
    t_loc = t // sp
    block_q, block_k, pad = _ring_blocks(t_loc, block_q, block_k)
    if kmask is None:
        kmask = jnp.ones((b, t), bool)

    def shard_body(qs, ks, vs, kms):
        # (B, T_loc, H, D) -> kernel layout (B, H, T_loc, D), padded.
        qs, ks, vs = (x.transpose(0, 2, 1, 3) for x in (qs, ks, vs))
        kmf = kms.astype(jnp.float32)[:, None, :]  # (B, 1, T_loc)
        if pad:
            cfg = ((0, 0), (0, 0), (0, pad), (0, 0))
            qs = jnp.pad(qs, cfg)
            ks = jnp.pad(ks, cfg)
            vs = jnp.pad(vs, cfg)
            kmf = jnp.pad(kmf, ((0, 0), (0, 0), (0, pad)))
        opts = (axis_name, causal, block_q, block_k, interpret)
        out = _ring_flash_core(qs, ks, vs, kmf, opts)
        if pad:
            out = out[:, :, :t_loc]
        return out.transpose(0, 2, 1, 3)

    # check_vma=False: pallas_call can't declare vma on its outputs, and
    # no vma-checked transpose rules are needed — the custom_vjp spells
    # out every collective in both directions itself.
    fn = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kmask)


def reference_attention(q, k, v, kmask=None, causal: bool = False):
    """Unsharded exact attention — the correctness oracle for tests."""
    s = _block_attend(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), kmask, None
    )
    if causal:
        t = q.shape[1]
        bias = jnp.where(
            jnp.arange(t)[:, None] >= jnp.arange(t)[None, :], 0.0, NEG_INF
        )
        s = s + bias[None, None]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


class RingSelfAttention(nn.Module):
    """Drop-in Flax self-attention block that runs ring attention when a
    mesh with sp>1 is supplied, falling back to vanilla attention.

    Used by the long-context transformer (models/longcontext.py); QKV/out
    projections are plain Dense layers, so they pick up tp sharding from
    the standard partition rules (parallel/sharding.py).
    """

    num_heads: int
    mesh: Mesh | None = None
    causal: bool = False
    dtype: jnp.dtype | None = None  # None = promote (bf16 when the train step casts params)
    # None = auto: the Pallas ring-flash path on TPU (O(block) memory,
    # bf16 MXU matmuls), the jnp ring elsewhere.
    use_flash: bool | None = None

    @nn.compact
    def __call__(self, x, kmask=None):
        b, t, hidden = x.shape
        head_dim = hidden // self.num_heads
        qkv = nn.DenseGeneral(
            (3, self.num_heads, head_dim), dtype=self.dtype, name="qkv"
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.mesh is not None and self.mesh.shape.get("sp", 1) > 1:
            use_flash = self.use_flash
            if use_flash is None:
                use_flash = jax.default_backend() == "tpu"
            attend = ring_flash_attention if use_flash else ring_attention
            o = attend(
                q, k, v, mesh=self.mesh, kmask=kmask, causal=self.causal
            )
        else:
            # reference_attention already returns q.dtype — no cast
            # (astype(None) would force f32 and pin the whole residual
            # stream there, defeating mixed precision).
            o = reference_attention(
                q, k, v, kmask=kmask, causal=self.causal
            )
        o = o.reshape(b, t, hidden)
        return nn.Dense(hidden, dtype=self.dtype, name="out")(o)
