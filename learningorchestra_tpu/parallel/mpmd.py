"""MPMD pipeline dispatch: every stage is its OWN compiled program.

The SPMD schedules in parallel/pipeline.py compile the whole pipeline
(fwd + bwd + optimizer for all stages) into ONE program — correct, but
the program's identity bakes in the full model, so a multi-chip fit
can never share compiles across jobs and was the explicit AOT-store
carve-out (a single executable spanning a mesh can't be serialized
per device).  "Scaling Deep Learning Training with MPMD Pipeline
Parallelism" (PAPERS.md) shows the alternative the TPU runtime already
supports: give each stage its own small program on its own chip and
let a host-side dispatcher run the microbatch schedule.

What that buys here:

- **Per-stage fingerprints.**  Each stage/embed/head program goes
  through ``CompiledProgramCache`` under its own key (module
  fingerprint + stage index + microbatch shape), so stage compiles are
  shared across jobs with the same architecture and — being
  single-device programs — are AOT-serializable: warm boot
  (train/aot_store.py) now covers multi-chip fits.
- **Overlap from enqueue order.**  JAX dispatch is async and each
  device executes its queue in FIFO order, so the host 1F1B loop below
  IS the schedule: enqueueing stage s's tick-t work before stage
  s+1's makes compute overlap the inter-stage ``device_put`` activation
  hops without any collective in any program.
- **Stage-partitioned state.**  Params/opt live as per-stage subtrees
  committed to their stage device: ``(embed, (stage_0, ..), head)``.
  Checkpoints write one orbax directory per partition and publish one
  top-level marker, so the PR-15 journal resume path restores every
  stage from its newest step after a SIGKILL.

The math is the SPMD 1F1B schedule's exactly: per-microbatch cotangent
seeds scaled ``w_m / gw`` (global masked-mean loss), rematerialize-in-
backward via ``jax.vjp`` on the saved stage input, one adam step per
batch from f32 master weights (optax adam is leafwise, so P+2
per-partition optimizer states step identically to one stacked state).
``tests/test_mpmd.py`` pins MPMD-vs-SPMD loss parity.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax

__all__ = ["MPMDEngine", "stage_devices", "partition_names"]


def stage_devices(mesh, n_stages: int) -> list:
    """One device per pipeline stage: walk the ``pp`` axis of the
    owner's mesh at index 0 of every other axis.  MPMD ignores the dp
    axis — scale batch via bigger microbatches instead."""
    names = list(mesh.axis_names)
    pp_ax = names.index("pp")
    grid = mesh.devices
    out = []
    idx = [0] * grid.ndim
    for s in range(n_stages):
        idx[pp_ax] = s
        out.append(grid[tuple(idx)])
    return out


def partition_names(n_stages: int) -> list[str]:
    """Checkpoint sub-directory names, in pipeline order."""
    return (
        ["embed"]
        + [f"stage_{s:02d}" for s in range(n_stages)]
        + ["head"]
    )


def _tree_avatars(tree):
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(np.shape(l), l.dtype), tree
    )


def _is_partitioned(middle) -> bool:
    """Stage params/opt arrive either STACKED (one subtree with a
    leading (pp,) axis — the SPMD layout and the host layout pickles
    carry) or PARTITIONED (a list/tuple with one subtree per stage —
    this engine's layout)."""
    return isinstance(middle, (list, tuple))


class _ProgramSet:
    """The cached per-shape program handles one fit (or predict shape)
    uses, plus their cache keys for cost aggregation."""

    def __init__(self, sig):
        self.sig = sig
        self.fns: dict = {}
        self.keys: dict = {}


class MPMDEngine:
    """Host-side MPMD dispatcher bound to one ``PipelinedTransformer``.

    Owns nothing persistent: params/opt_state stay on the owner (the
    fit/checkpoint surface), programs live in the process-wide
    ``CompiledProgramCache``.  The engine is dropped on pickle and
    lazily rebuilt."""

    def __init__(self, owner):
        from learningorchestra_tpu.train.neural import _param_cast_for

        self.o = owner
        self.pp = int(owner.pp)
        self.devices = stage_devices(owner.mesh, self.pp)
        self._pcast = _param_cast_for(
            jnp.bfloat16 if owner.compute_dtype == "bfloat16" else None
        )
        self._train: _ProgramSet | None = None
        self._fwd: _ProgramSet | None = None
        self._placed = False
        self._stage_s = [0.0] * self.pp
        self._batch_cost = None  # per-batch aggregate ProgramCost

    # -- placement ------------------------------------------------------------

    def ensure_placed(self) -> None:
        """Commit the owner's state to the per-stage layout: embed on
        the first stage device, stage s's subtree on device s, head on
        the last.  Accepts the stacked SPMD/host layout and splits it;
        re-entry after placement is a flag check."""
        if self._placed:
            return
        o = self.o
        if o.params is None:
            return
        ep, sp, hp = o.params
        if not _is_partitioned(sp):
            sp = tuple(
                jax.tree_util.tree_map(lambda l, s=s: l[s], sp)
                for s in range(self.pp)
            )
        devs = self.devices
        ep = jax.device_put(ep, devs[0])
        sp = tuple(
            jax.device_put(sp[s], devs[s]) for s in range(self.pp)
        )
        hp = jax.device_put(hp, devs[-1])
        o.params = (ep, sp, hp)

        opt = o.opt_state
        if opt is not None and _is_partitioned(
            opt[1] if isinstance(opt, tuple) and len(opt) == 3
            and not hasattr(opt, "_fields") else None
        ):
            oe, osp, oh = opt
            o.opt_state = (
                jax.device_put(oe, devs[0]),
                tuple(
                    jax.device_put(osp[s], devs[s])
                    for s in range(self.pp)
                ),
                jax.device_put(oh, devs[-1]),
            )
        else:
            # Stacked (or missing) optimizer state can't be split into
            # per-stage adam counts — re-init fresh moments per
            # partition (the restore-best contract: moments belong to
            # the run that makes them).
            self._init_opt()
        self._placed = True

    def _init_opt(self) -> None:
        """Per-partition optimizer states.  optax transforms are
        leafwise, so P+2 independent states updated once per batch are
        numerically identical to one stacked state."""
        o = self.o
        ep, sp, hp = o.params
        init = jax.jit(o.optimizer.init)
        o.opt_state = (
            init(ep),
            tuple(init(sp[s]) for s in range(self.pp)),
            init(hp),
        )

    # -- compiled-program plumbing -------------------------------------------

    def _cached(self, pset, name, kind, *, module, shapes, builder,
                donate=None, with_opt=False, cost_args=None):
        """One program through the process-wide compile cache.  Keys
        carry the PART identity (kind includes the stage index), so N
        stages yield N independent, AOT-eligible entries."""
        from learningorchestra_tpu.train import compile_cache as cc
        from learningorchestra_tpu.train.neural import (
            _probe_program_cost,
        )

        o = self.o
        key = cc.program_key(
            f"mpmd:{kind}",
            module=cc.module_fingerprint(module),
            optimizer=cc.optimizer_fingerprint(o) if with_opt else None,
            loss="softmax_ce",
            dtype=o.compute_dtype,
            shapes=shapes,
            mesh=None,
            donate=donate,
        )
        label = f"mpmd:{type(o).__name__}:{kind}"

        def building():
            fn = builder()
            if cost_args is not None:
                # Single-device, collective-free lowering: the probe's
                # flops/bytes are per-stage honest, and the serialized
                # executable is AOT-store eligible — the multi-chip
                # warm-boot carve-out closes here.
                _probe_program_cost(
                    key, label, fn, cost_args,
                    aot_eligible=True,
                    collectives_excluded=True,
                )
            return fn

        fn = cc.get_cache().get_or_build(key, building, label=label)
        pset.fns[name] = fn
        pset.keys[name] = key
        return fn

    def _prepare_train(self, mb_sz: int, seq_len: int,
                       y_shape: tuple) -> _ProgramSet:
        sig = (mb_sz, seq_len, tuple(y_shape))
        if self._train is not None and self._train.sig == sig:
            return self._train
        o = self.o
        pcast = self._pcast
        embed, stage, head = o._embed, o._stage, o._head
        loss_fn = o._loss_fn
        f32 = jnp.float32
        tree = jax.tree_util

        ep, sp, hp = o.params
        ep_av, sp_av, hp_av = (
            _tree_avatars(ep), _tree_avatars(sp[0]), _tree_avatars(hp)
        )
        tok_av = jax.ShapeDtypeStruct((mb_sz, seq_len), jnp.int32)
        h_av = jax.eval_shape(
            lambda p, t: embed.apply(pcast(p), t), ep_av, tok_av
        )
        km_av = jax.ShapeDtypeStruct((mb_sz, seq_len), jnp.bool_)
        y_av = jax.ShapeDtypeStruct((mb_sz, *y_shape), jnp.int32)
        m_av = jax.ShapeDtypeStruct((mb_sz,), f32)
        logits_av = jax.eval_shape(
            lambda p, h: head.apply(pcast(p), h), hp_av, h_av
        )
        _, metrics_av = jax.eval_shape(
            lambda l, y, m: loss_fn(l.astype(f32), y, m),
            logits_av, y_av, m_av,
        )
        scalar_av = jax.ShapeDtypeStruct((), f32)

        def embed_fwd(p, tok):
            return embed.apply(pcast(p), tok)

        def embed_bwd(p, tok, dh, acc):
            _, vjp = jax.vjp(lambda q: embed.apply(pcast(q), tok), p)
            (dp_,) = vjp(dh)
            return tree.tree_map(jnp.add, acc, dp_)

        def stage_fwd(p, x, km):
            return stage.apply(pcast(p), x, km)

        def stage_bwd(p, x, km, cot, acc):
            # Rematerialize-in-backward: re-apply the stage under vjp
            # on the SAVED input — the same FLOPs-for-HBM trade the
            # SPMD 1F1B schedule makes.
            _, vjp = jax.vjp(
                lambda q, xx: stage.apply(pcast(q), xx, km), p, x
            )
            dp_, dx = vjp(cot)
            return tree.tree_map(jnp.add, acc, dp_), dx

        def head_bwd(p, h, y, m, inv_gw, acc, macc, wacc):
            def head_loss(q, hh):
                logits = head.apply(pcast(q), hh).astype(f32)
                return loss_fn(logits, y, m)

            loss_m, vjp, metrics_m = jax.vjp(
                head_loss, p, h, has_aux=True
            )
            del loss_m  # metrics carry "loss"; accumulated below
            w_m = m.sum().astype(f32)
            # Seed = w_m/gw: the stitched gradient equals the gradient
            # of the SPMD schedules' global masked-mean loss.
            dp_, dh = vjp(w_m * inv_gw)
            acc = tree.tree_map(jnp.add, acc, dp_)
            macc = tree.tree_map(
                lambda a, v: a + w_m * v, macc, metrics_m
            )
            return dh, acc, macc, wacc + w_m

        def zeros_like_tree(p):
            return tree.tree_map(jnp.zeros_like, p)

        def head_zeros(p):
            return (
                tree.tree_map(jnp.zeros_like, p),
                tree.tree_map(
                    lambda a: jnp.zeros(a.shape, a.dtype), metrics_av
                ),
                jnp.zeros((), f32),
            )

        def finalize(macc, wacc):
            gw = jnp.maximum(wacc, 1e-9)
            return tree.tree_map(lambda v: v / gw, macc)

        def opt_step(p, s_, g):
            # f32 master weights; grads come back f32 through the
            # cast-inside-vjp, the astype is the neural.py contract.
            g = tree.tree_map(
                lambda gg, pp_: gg.astype(pp_.dtype), g, p
            )
            updates, s_ = o.optimizer.update(g, s_, p)
            return optax.apply_updates(p, updates), s_

        oe, osp, oh = (
            o.opt_state if o.opt_state is not None
            else (None, (None,) * self.pp, None)
        )
        pset = _ProgramSet(sig)
        mb = (mb_sz, seq_len)
        self._cached(
            pset, "embed:fwd", "embed:fwd", module=embed, shapes=mb,
            builder=lambda: jax.jit(embed_fwd),
            cost_args=lambda: (ep_av, tok_av),
        )
        self._cached(
            pset, "embed:bwd", "embed:bwd", module=embed, shapes=mb,
            donate=(3,),
            builder=lambda: jax.jit(embed_bwd, donate_argnums=(3,)),
            cost_args=lambda: (ep_av, tok_av, h_av, ep_av),
        )
        self._cached(
            pset, "embed:zeros", "embed:zeros", module=embed, shapes=mb,
            builder=lambda: jax.jit(zeros_like_tree),
            cost_args=lambda: (ep_av,),
        )
        if oe is not None:
            self._cached(
                pset, "embed:opt", "embed:opt", module=embed, shapes=mb,
                with_opt=True, donate=(0, 1, 2),
                builder=lambda: jax.jit(
                    opt_step, donate_argnums=(0, 1, 2)
                ),
                cost_args=lambda: (ep_av, _tree_avatars(oe), ep_av),
            )
        for s in range(self.pp):
            self._cached(
                pset, ("stage:fwd", s), f"stage:fwd:s{s}", module=stage,
                shapes=mb,
                builder=lambda: jax.jit(stage_fwd),
                cost_args=lambda: (sp_av, h_av, km_av),
            )
            self._cached(
                pset, ("stage:bwd", s), f"stage:bwd:s{s}", module=stage,
                shapes=mb, donate=(4,),
                builder=lambda: jax.jit(
                    stage_bwd, donate_argnums=(4,)
                ),
                cost_args=lambda: (sp_av, h_av, km_av, h_av, sp_av),
            )
            self._cached(
                pset, ("stage:zeros", s), f"stage:zeros:s{s}",
                module=stage, shapes=mb,
                builder=lambda: jax.jit(zeros_like_tree),
                cost_args=lambda: (sp_av,),
            )
            if osp[s] is not None:
                self._cached(
                    pset, ("stage:opt", s), f"stage:opt:s{s}",
                    module=stage, shapes=mb, with_opt=True,
                    donate=(0, 1, 2),
                    builder=lambda: jax.jit(
                        opt_step, donate_argnums=(0, 1, 2)
                    ),
                    cost_args=lambda: (
                        sp_av, _tree_avatars(osp[0]), sp_av
                    ),
                )
        self._cached(
            pset, "head:bwd", "head:bwd", module=head, shapes=mb,
            donate=(5, 6, 7),
            builder=lambda: jax.jit(
                head_bwd, donate_argnums=(5, 6, 7)
            ),
            cost_args=lambda: (
                hp_av, h_av, y_av, m_av, scalar_av, hp_av, metrics_av,
                scalar_av,
            ),
        )
        self._cached(
            pset, "head:zeros", "head:zeros", module=head, shapes=mb,
            builder=lambda: jax.jit(head_zeros),
            cost_args=lambda: (hp_av,),
        )
        self._cached(
            pset, "head:finalize", "head:finalize", module=head,
            shapes=mb,
            builder=lambda: jax.jit(finalize),
            cost_args=lambda: (metrics_av, scalar_av),
        )
        if oh is not None:
            self._cached(
                pset, "head:opt", "head:opt", module=head, shapes=mb,
                with_opt=True, donate=(0, 1, 2),
                builder=lambda: jax.jit(
                    opt_step, donate_argnums=(0, 1, 2)
                ),
                cost_args=lambda: (hp_av, _tree_avatars(oh), hp_av),
            )
        self._train = pset
        self._batch_cost = self._aggregate_batch_cost(pset)
        return pset

    # -- the 1F1B host schedule ----------------------------------------------

    def train_batch(self, xb: np.ndarray, yb: np.ndarray,
                    mask: np.ndarray):
        """One optimizer step over one global batch, scheduled 1F1B
        across the stage devices.  Enqueue order is the schedule:
        dispatch is async and each device drains its queue FIFO, so
        tick t's stage-s forward is in flight while the tick-(t-1)
        activation hop lands on stage s+1.  Returns the DEVICE metrics
        dict and the batch's real-row weight — the owner's
        ``_weighted_update`` consumes both unchanged."""
        o = self.o
        self.ensure_placed()
        if o.opt_state is None:  # restore-best dropped the moments
            self._init_opt()
        P = self.pp
        M = int(o.n_micro)
        B = xb.shape[0]
        mb_sz = B // M
        yb = np.asarray(yb)
        pset = self._prepare_train(mb_sz, xb.shape[1], yb.shape[1:])
        fns = pset.fns
        devs = self.devices
        clock = time.perf_counter

        xm = np.asarray(xb, np.int32).reshape(M, mb_sz, *xb.shape[1:])
        ym = yb.astype(np.int32).reshape(M, mb_sz, *yb.shape[1:])
        mm = np.asarray(mask, np.float32).reshape(M, mb_sz)
        km = xm != 0  # (M, mb, T) pad id 0
        gw = float(mask.sum())
        inv_gw = jax.device_put(
            np.float32(1.0 / max(gw, 1e-9)), devs[-1]
        )

        tok = [jax.device_put(xm[i], devs[0]) for i in range(M)]
        km_d = [
            [jax.device_put(km[i], devs[s]) for i in range(M)]
            for s in range(P)
        ]
        y_d = [jax.device_put(ym[i], devs[-1]) for i in range(M)]
        w_d = [jax.device_put(mm[i], devs[-1]) for i in range(M)]

        ep, sp, hp = o.params
        oe, osp, oh = o.opt_state
        sp = list(sp)
        osp = list(osp)
        acc_e = fns["embed:zeros"](ep)
        acc_s = [fns[("stage:zeros", s)](sp[s]) for s in range(P)]
        acc_h, macc, wacc = fns["head:zeros"](hp)

        saved = [[None] * M for _ in range(P)]  # stage inputs (remat)
        inbox = [[None] * M for _ in range(P)]  # activations arriving
        cotbox = [[None] * M for _ in range(P)]  # cotangents arriving
        dh_seed = [None] * M

        stage_s = self._stage_s
        for t in range(M + 2 * P - 2):
            # ---- forward slots: stage s runs microbatch t - s ----
            for s in range(P):
                m = t - s
                if not 0 <= m < M:
                    continue
                t0 = clock()
                if s == 0:
                    x_in = fns["embed:fwd"](ep, tok[m])
                else:
                    x_in = inbox[s][m]
                    inbox[s][m] = None
                saved[s][m] = x_in
                out = fns[("stage:fwd", s)](sp[s], x_in, km_d[s][m])
                if s + 1 < P:
                    nxt = jax.device_put(out, devs[s + 1])
                    stage_s[s] += clock() - t0
                    inbox[s + 1][m] = nxt
                else:
                    # 1F1B: the head+loss VJP seeds microbatch m's
                    # cotangent the very tick its forward completes.
                    dh, acc_h, macc, wacc = fns["head:bwd"](
                        hp, out, y_d[m], w_d[m], inv_gw,
                        acc_h, macc, wacc,
                    )
                    dh_seed[m] = dh
                    stage_s[s] += clock() - t0
            # ---- backward slots: stage s runs microbatch
            # t - 2P + 2 + s (last stage first — its seed is fresh) ---
            for s in range(P - 1, -1, -1):
                m = t - 2 * P + 2 + s
                if not 0 <= m < M:
                    continue
                t0 = clock()
                if s == P - 1:
                    cot = dh_seed[m]
                    dh_seed[m] = None
                else:
                    cot = cotbox[s][m]
                    cotbox[s][m] = None
                x_saved = saved[s][m]
                saved[s][m] = None
                acc_s[s], dx = fns[("stage:bwd", s)](
                    sp[s], x_saved, km_d[s][m], cot, acc_s[s]
                )
                if s > 0:
                    cotbox[s - 1][m] = jax.device_put(dx, devs[s - 1])
                else:
                    acc_e = fns["embed:bwd"](ep, tok[m], dx, acc_e)
                stage_s[s] += clock() - t0

        t0 = clock()
        ep, oe = fns["embed:opt"](ep, oe, acc_e)
        stage_s[0] += clock() - t0
        for s in range(P):
            t0 = clock()
            sp[s], osp[s] = fns[("stage:opt", s)](sp[s], osp[s],
                                                  acc_s[s])
            stage_s[s] += clock() - t0
        t0 = clock()
        hp, oh = fns["head:opt"](hp, oh, acc_h)
        metrics = fns["head:finalize"](macc, wacc)
        stage_s[P - 1] += clock() - t0

        o.params = (ep, tuple(sp), hp)
        o.opt_state = (oe, tuple(osp), oh)
        return metrics, gw

    # -- inference ------------------------------------------------------------

    def forward_logits(self, chunk: np.ndarray):
        """Sequential forward across the stage devices (inference
        needs no microbatch schedule): tokens to stage 0, activations
        hop stage to stage, logits land on the last device."""
        o = self.o
        self.ensure_placed()
        sig = ("fwd", chunk.shape)
        if self._fwd is None or self._fwd.sig != sig:
            pcast = self._pcast
            embed, stage, head = o._embed, o._stage, o._head
            ep, sp, hp = o.params
            tok_av = jax.ShapeDtypeStruct(chunk.shape, jnp.int32)
            km_av = jax.ShapeDtypeStruct(chunk.shape, jnp.bool_)
            h_av = jax.eval_shape(
                lambda p, t: embed.apply(pcast(p), t),
                _tree_avatars(ep), tok_av,
            )
            pset = _ProgramSet(sig)
            self._cached(
                pset, "embed:fwd", "embed:fwd", module=embed,
                shapes=chunk.shape,
                builder=lambda: jax.jit(
                    lambda p, t: embed.apply(pcast(p), t)
                ),
                cost_args=lambda: (_tree_avatars(ep), tok_av),
            )
            for s in range(self.pp):
                self._cached(
                    pset, ("stage:fwd", s), f"stage:fwd:s{s}",
                    module=stage, shapes=chunk.shape,
                    builder=lambda: jax.jit(
                        lambda p, x, km: stage.apply(pcast(p), x, km)
                    ),
                    cost_args=lambda: (
                        _tree_avatars(sp[0]), h_av, km_av
                    ),
                )
            self._cached(
                pset, "head:fwd", "head:fwd", module=head,
                shapes=chunk.shape,
                builder=lambda: jax.jit(
                    lambda p, h: head.apply(pcast(p), h)
                ),
                cost_args=lambda: (_tree_avatars(hp), h_av),
            )
            self._fwd = pset
        fns = self._fwd.fns
        devs = self.devices
        ep, sp, hp = o.params
        tok = jax.device_put(np.asarray(chunk, np.int32), devs[0])
        km = jax.device_put(chunk != 0, devs[0])
        h = fns["embed:fwd"](ep, tok)
        for s in range(self.pp):
            if s > 0:
                h = jax.device_put(h, devs[s])
                km = jax.device_put(np.asarray(chunk != 0), devs[s])
            h = fns[("stage:fwd", s)](sp[s], h, km)
        return fns["head:fwd"](hp, jax.device_put(h, devs[-1]))

    # -- observability --------------------------------------------------------

    def pop_stage_seconds(self) -> list[float]:
        """Per-stage host dispatch seconds accumulated since the last
        call — the owner turns these into ``mpmd.stage`` trace spans
        once per epoch."""
        out = list(self._stage_s)
        self._stage_s = [0.0] * self.pp
        return out

    def _aggregate_batch_cost(self, pset):
        """One ProgramCost for a whole batch: per-microbatch program
        costs × n_micro plus the once-per-batch optimizer/finalize
        programs.  Collectives are excluded BY CONSTRUCTION — no MPMD
        program contains one — so job MFU from this number is honest
        for multi-chip fits."""
        from learningorchestra_tpu.obs import costs as obs_costs

        if not obs_costs.enabled():
            return None
        ledger = obs_costs.get_ledger()
        M = int(self.o.n_micro)
        per_micro = ["embed:fwd", "embed:bwd", "head:bwd"] + [
            (k, s) for s in range(self.pp)
            for k in ("stage:fwd", "stage:bwd")
        ]
        per_batch = (
            ["embed:opt", "head:opt", "head:finalize"]
            + [("stage:opt", s) for s in range(self.pp)]
        )
        flops = 0.0
        nbytes = 0.0
        analyzed = False
        for name, mult in (
            [(n, M) for n in per_micro] + [(n, 1) for n in per_batch]
        ):
            key = pset.keys.get(name)
            cost = ledger.get(key) if key else None
            if cost is None or not cost.analyzed:
                continue
            analyzed = True
            flops += (cost.flops or 0.0) * mult
            nbytes += (cost.bytes_accessed or 0.0) * mult
        if not analyzed:
            return None
        return obs_costs.ProgramCost(
            key=f"mpmd:batch:{pset.keys.get('head:bwd', '')[:12]}",
            label=f"mpmd:{type(self.o).__name__}:batch",
            flops=flops or None,
            bytes_accessed=nbytes or None,
            analyzed=True,
            collectives_excluded=True,
        )

    def attribute_epoch(self, epoch_s: float, n_batches: int) -> None:
        """One epoch's device interval into the per-job ledger with
        the aggregate MPMD flops attached (collectives excluded)."""
        from learningorchestra_tpu.obs import costs as obs_costs

        cost = self._batch_cost
        if cost is None or not obs_costs.enabled():
            return
        try:
            import dataclasses

            obs_costs.attribute(
                epoch_s,
                cost=dataclasses.replace(
                    cost,
                    flops=(cost.flops or 0.0) * n_batches or None,
                    bytes_accessed=(
                        (cost.bytes_accessed or 0.0) * n_batches
                        or None
                    ),
                ),
            )
        except Exception:  # noqa: BLE001 — accounting never fails a fit
            pass

    def epoch_cost_attrs(self, epoch_s: float,
                         n_batches: int) -> dict:
        """flops/MFU span annotations mirroring neural.py's
        ``_epoch_cost_attrs`` for the per-epoch trace span."""
        from learningorchestra_tpu.obs import costs as obs_costs

        cost = self._batch_cost
        if cost is None or cost.flops is None:
            return {}
        flops = cost.flops * n_batches
        attrs = {"flops": flops, "collectivesExcluded": True}
        try:
            util = obs_costs.mfu(
                flops, epoch_s, peak_flops=obs_costs.peak_flops()
            )
        except Exception:  # noqa: BLE001
            util = None
        if util is not None:
            attrs["mfu"] = util
        return attrs

    # -- stage-partitioned checkpoints ---------------------------------------

    def save_checkpoint(self, directory, step: int, history: dict,
                        *, async_save: bool = True) -> None:
        """One orbax directory per partition, then ONE top-level
        marker.  Async saves overlap the P+2 device→host transfers;
        the marker publishes only after every partition commits, so
        the journal's top-level ``latest.json`` wait (and a resuming
        fit) never sees a torn multi-stage checkpoint."""
        from learningorchestra_tpu.train import checkpoint as ckpt

        self.ensure_placed()
        o = self.o
        if o.opt_state is None:  # restore-best dropped the moments
            self._init_opt()
        d = Path(directory)
        for name, part, opt in self._parts():
            ckpt.save(
                d / name, step, {"params": part, "opt_state": opt},
                history=None, async_save=async_save,
            )
        if async_save:
            for name in partition_names(self.pp):
                ckpt.finalize_async(d / name)
        ckpt.publish_marker(d, step, history)

    def resume_checkpoint(self, directory):
        """Restore every partition from the newest COMMON step.  Each
        partition dir carries its own marker; the resume step is the
        minimum — a SIGKILL between partition saves resumes from the
        last step every stage completed.  Returns ``(step, history)``
        or None."""
        from learningorchestra_tpu.train import checkpoint as ckpt

        self.ensure_placed()
        o = self.o
        if o.params is None:
            return None
        d = Path(directory)
        names = partition_names(self.pp)
        steps = []
        for name in names:
            marker = d / name / "latest.json"
            if not marker.exists():
                return None
            try:
                steps.append(
                    int(json.loads(marker.read_text())["step"])
                )
            except (ValueError, KeyError, json.JSONDecodeError):
                return None
        step = min(steps)
        if o.opt_state is None:
            self._init_opt()
        restored = []
        for (name, part, opt) in self._parts():
            template = {"params": part, "opt_state": opt}
            state = ckpt.load_step(d / name, step, template)
            if state is None:
                # The common step was pruned in one partition (KEEP
                # window) — resume has nothing consistent to offer.
                return None
            restored.append(state)
        ep_s, *st_s, hp_s = restored
        # Orbax restores onto the default device; re-commit every
        # partition to ITS stage device or the first post-resume
        # dispatch mixes devices inside one jitted call.
        devs = self.devices
        o.params = (
            jax.device_put(ep_s["params"], devs[0]),
            tuple(
                jax.device_put(s["params"], devs[i])
                for i, s in enumerate(st_s)
            ),
            jax.device_put(hp_s["params"], devs[-1]),
        )
        o.opt_state = (
            jax.device_put(ep_s["opt_state"], devs[0]),
            tuple(
                jax.device_put(s["opt_state"], devs[i])
                for i, s in enumerate(st_s)
            ),
            jax.device_put(hp_s["opt_state"], devs[-1]),
        )
        history: dict = {}
        top = d / "latest.json"
        if top.exists():
            try:
                marker = json.loads(top.read_text())
                if int(marker.get("step", -1)) == step:
                    history = marker.get("history") or {}
            except (ValueError, json.JSONDecodeError):
                history = {}
        return step, history

    def finalize_checkpoints(self, directory) -> None:
        from learningorchestra_tpu.train import checkpoint as ckpt

        d = Path(directory)
        for name in partition_names(self.pp):
            ckpt.finalize_async(d / name)

    def _parts(self):
        """(name, params, opt_state) per partition, pipeline order —
        matches :func:`partition_names`."""
        o = self.o
        ep, sp, hp = o.params
        oe, osp, oh = (
            o.opt_state if o.opt_state is not None
            else (None, (None,) * self.pp, None)
        )
        yield "embed", ep, oe
        for s in range(self.pp):
            yield f"stage_{s:02d}", sp[s], osp[s]
        yield "head", hp, oh
