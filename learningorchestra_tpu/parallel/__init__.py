"""Distributed execution: device meshes, sharded training, ring attention,
and the multi-host control plane.

This package replaces the reference's entire distributed stack — Horovod's
C++ ring-allreduce over Gloo, the Ray cluster scheduler, and the
ship-model-as-JSON / return-weights-as-lists serialization (reference:
microservices/binary_executor_image/binary_execution.py:203-292,
training_function/train_function.py:53-139, ray_cluster/Dockerfile:14) —
with the TPU-native equivalents:

- ``mesh``: named device meshes (dp/fsdp/tp/sp axes) over ICI;
- ``sharding``: partition rules mapping model pytrees and batches onto the
  mesh so XLA's SPMD partitioner inserts the collectives (psum over dp for
  gradients — the compiled replacement for Horovod's host-side ring);
- ``distributed``: ``DistributedTrainer``, the mesh-sharded train loop;
- ``ring_attention``: blockwise ring attention over the ``sp`` axis
  (ppermute under shard_map) for long-context sequence parallelism;
- ``coordinator``: multi-host bootstrap (``jax.distributed.initialize``)
  plus the framework's own coordinator/host-agent control plane replacing
  Ray client + GCS (SURVEY §5.8).
"""

import jax as _jax

# ``jax.shard_map`` only graduated out of ``jax.experimental`` in newer
# releases; on the pinned 0.4.x line the top-level name does not exist.
# Install it so every call site (and user code written against the new
# spelling) runs on both.
if not hasattr(_jax, "shard_map"):  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

    _jax.shard_map = _shard_map

from learningorchestra_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    default_spec,
)
from learningorchestra_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    param_shardings,
)
from learningorchestra_tpu.parallel.distributed import (  # noqa: F401
    DistributedTrainer,
)
from learningorchestra_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
)
from learningorchestra_tpu.parallel.pipeline import (  # noqa: F401
    PipelinedTransformer,
)
