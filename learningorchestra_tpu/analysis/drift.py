"""Cross-artifact drift gates.

The orchestration contract this repo re-expresses — ``LO_TPU_*`` env
knobs, REST routes, Prometheus family names, armable fault points —
lives in five places at once: the code that reads it, ``config.py``,
the README knob tables, and both deploy manifests.  Nothing but
convention keeps them in sync; these gates make the convention
mechanical.

Rules (all error severity):

``knob-missing-config``    knob referenced in code but absent from
                           ``config.py`` (the canonical index —
                           direct-read knobs belong in its
                           ``DIRECT_ENV_KNOBS`` registry)
``knob-missing-compose``   knob absent from deploy/docker-compose.yml
``knob-missing-k8s``       knob absent from deploy/k8s.yaml
``knob-missing-readme``    knob absent from the README knob tables
``knob-unknown``           knob present in a manifest/README but
                           referenced nowhere in code (stale entry)
``fault-point-unknown``    ``LO_TPU_FAULT_<X>`` / ``faults.hit("x")``
                           names a point faults/plane.py never
                           registers
``route-missing-client``   a REST route with no client.py binding
``route-gate-missing``     the every-route-metered test gate is gone
``metric-unregistered``    a ``lo_*`` family named in tests/README
                           that no registry call creates
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

from .findings import Finding

_KNOB_RE = re.compile(r"LO_TPU_[A-Z0-9_]+")
_FAMILY_RE = re.compile(r"(?<![A-Za-z0-9_])lo_[a-z0-9_]+")
_GROUP_RE = re.compile(r"\(\?P<[A-Za-z_]+>[^)]*\)")
_PROM_SUFFIXES = ("_bucket", "_sum", "_count")
#: ``lo_``-prefixed tokens that are not metric families: the check
#: tool's own name shows up in test/README strings.
_FAMILY_IGNORE = {"lo_check"}


@dataclasses.dataclass
class DriftPaths:
    """Where each artifact lives — parameterized so golden tests can
    point the gates at fixture copies."""

    package_root: Path
    config: Path
    compose: Path
    k8s: Path
    readme: Path
    server: Path
    client: Path
    plane: Path
    tests_dir: Path
    scripts: tuple = ()

    @staticmethod
    def for_repo(repo_root: str | Path) -> "DriftPaths":
        root = Path(repo_root)
        pkg = root / "learningorchestra_tpu"
        return DriftPaths(
            package_root=pkg,
            config=pkg / "config.py",
            compose=root / "deploy" / "docker-compose.yml",
            k8s=root / "deploy" / "k8s.yaml",
            readme=root / "README.md",
            server=pkg / "api" / "server.py",
            client=pkg / "client.py",
            plane=pkg / "faults" / "plane.py",
            tests_dir=root / "tests",
            scripts=tuple(
                sorted((root / "scripts").glob("*"))
            ) + ((root / "bench.py"),) if (root / "scripts").exists()
            else (),
        )


def _read(path: Path) -> str:
    try:
        return path.read_text()
    except OSError:
        return ""


class _Sources:
    """Read/parse-once cache over the artifact set.  An unparsable
    file yields ``None`` (the runner reports package syntax errors
    separately; the drift gates must degrade, not crash the CLI)."""

    def __init__(self):
        self._texts: dict[Path, str] = {}
        self._trees: dict[Path, ast.Module | None] = {}

    def text(self, path: Path) -> str:
        if path not in self._texts:
            self._texts[path] = _read(path)
        return self._texts[path]

    def tree(self, path: Path) -> ast.Module | None:
        if path not in self._trees:
            try:
                self._trees[path] = ast.parse(self.text(path))
            except SyntaxError:
                self._trees[path] = None
        return self._trees[path]


def _package_files(paths: DriftPaths):
    for p in sorted(paths.package_root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p


def _knob_tokens(text: str):
    """Full LO_TPU_* tokens; trailing-underscore hits are prefix
    mentions (``LO_TPU_SERVE_*``-style docs), not knobs."""
    for m in _KNOB_RE.finditer(text):
        tok = m.group(0)
        if not tok.endswith("_"):
            yield tok, m.start()


def _first_site(text: str, token: str, path: Path):
    idx = text.find(token)
    line = text.count("\n", 0, idx) + 1 if idx >= 0 else 1
    return str(path), line


# -- fault points ------------------------------------------------------------


def registered_fault_points(
    paths: DriftPaths, src: "_Sources | None" = None
) -> set[str]:
    """POINTS tuple literal in plane.py + register_point("...") call
    literals anywhere in the package."""
    src = src or _Sources()
    points: set[str] = set()
    plane_tree = src.tree(paths.plane)
    for node in (plane_tree.body if plane_tree else ()):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "POINTS"
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    points.add(elt.value)
    for p in _package_files(paths):
        tree = src.tree(p)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and (
                    (isinstance(node.func, ast.Name)
                     and node.func.id == "register_point")
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "register_point")
                )
                and node.args
                and isinstance(node.args[0], ast.Constant)
            ):
                points.add(node.args[0].value)
    return points


def _env_spelling(point: str) -> str:
    return point.upper().replace(".", "_")


def check_fault_points(
    paths: DriftPaths, src: "_Sources | None" = None
) -> list[Finding]:
    src = src or _Sources()
    points = registered_fault_points(paths, src)
    env_ok = {_env_spelling(p) for p in points}
    findings: list[Finding] = []
    # LO_TPU_FAULT_<X> spellings anywhere an operator could write one.
    surfaces = (
        list(_package_files(paths))
        + [paths.compose, paths.k8s, paths.readme]
        + sorted(paths.tests_dir.glob("test_*.py"))
        + [Path(s) for s in paths.scripts]
    )
    for p in surfaces:
        text = src.text(Path(p))
        for tok, pos in _knob_tokens(text):
            if not tok.startswith("LO_TPU_FAULT_"):
                continue
            suffix = tok[len("LO_TPU_FAULT_"):]
            if suffix and suffix not in env_ok:
                line = text.count("\n", 0, pos) + 1
                findings.append(Finding(
                    str(p), line, "fault-point-unknown",
                    f"{tok} names no registered fault point "
                    f"(known: {', '.join(sorted(points))})",
                ))
    # faults.hit("x") / arm("x") literals in the package.
    for p in _package_files(paths):
        if p == paths.plane:
            continue
        tree = src.tree(p)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else ""
            )
            if name in ("hit", "arm") and "." in node.args[0].value:
                point = node.args[0].value
                if point not in points:
                    findings.append(Finding(
                        str(p), node.lineno, "fault-point-unknown",
                        f"faults.{name}({point!r}) names no "
                        "registered fault point",
                    ))
    return findings


# -- env knobs ---------------------------------------------------------------


def check_knobs(
    paths: DriftPaths, src: "_Sources | None" = None
) -> list[Finding]:
    src = src or _Sources()
    findings: list[Finding] = []
    code_refs: dict[str, tuple] = {}
    for p in list(_package_files(paths)) + [
        Path(s) for s in paths.scripts
    ]:
        text = src.text(p)
        for tok, pos in _knob_tokens(text):
            if tok.startswith("LO_TPU_FAULT_"):
                continue  # fault-point rule's jurisdiction
            if tok not in code_refs:
                line = text.count("\n", 0, pos) + 1
                code_refs[tok] = (str(p), line)

    config_text = src.text(paths.config)
    compose_text = src.text(paths.compose)
    k8s_text = src.text(paths.k8s)
    readme_text = src.text(paths.readme)

    for tok in sorted(code_refs):
        site = code_refs[tok]
        for artifact_text, rule, what in (
            (config_text, "knob-missing-config",
             "config.py (the canonical knob index)"),
            (compose_text, "knob-missing-compose",
             "deploy/docker-compose.yml"),
            (k8s_text, "knob-missing-k8s", "deploy/k8s.yaml"),
            (readme_text, "knob-missing-readme",
             "the README knob tables"),
        ):
            if tok not in artifact_text:
                findings.append(Finding(
                    site[0], site[1], rule,
                    f"{tok} is referenced in code but absent from "
                    f"{what}",
                ))
    # Reverse direction: manifest/README entries no code reads are
    # stale — a renamed knob's old spelling silently configuring
    # nothing.
    for artifact, path in (
        (compose_text, paths.compose),
        (k8s_text, paths.k8s),
        (readme_text, paths.readme),
    ):
        for tok, pos in _knob_tokens(artifact):
            if tok.startswith("LO_TPU_FAULT_"):
                continue
            if tok not in code_refs and tok not in config_text:
                line = artifact.count("\n", 0, pos) + 1
                findings.append(Finding(
                    str(path), line, "knob-unknown",
                    f"{tok} appears here but no code reads it — "
                    "stale entry or typo",
                ))
    return findings


# -- routes ------------------------------------------------------------------


def server_routes(
    paths: DriftPaths, src: "_Sources | None" = None
) -> list[tuple]:
    """→ [(verb, template, line)] where template segments are literal
    strings or "*" for a regex group."""
    tree = (src or _Sources()).tree(paths.server)
    if tree is None:
        return []
    # Literal string assignments anywhere (TOOL/NAME pattern vars).
    consts: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = node.value.value

    def resolve(expr) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(
            expr.value, str
        ):
            return expr.value
        if isinstance(expr, ast.Name):
            return consts.get(expr.id)
        if isinstance(expr, ast.JoinedStr):
            parts = []
            for val in expr.values:
                if isinstance(val, ast.Constant):
                    parts.append(str(val.value))
                elif isinstance(val, ast.FormattedValue):
                    inner = resolve(val.value)
                    if inner is None:
                        return None
                    parts.append(inner)
            return "".join(parts)
        if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, ast.Add
        ):
            left, right = resolve(expr.left), resolve(expr.right)
            if left is not None and right is not None:
                return left + right
        return None

    routes = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "add"
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Constant)
        ):
            continue
        verb = node.args[0].value
        raw = resolve(node.args[1])
        if raw is None:
            continue
        template = _GROUP_RE.sub("*", raw)
        routes.append((verb, template, node.lineno))
    return routes


def client_templates(
    paths: DriftPaths, src: "_Sources | None" = None
) -> list[tuple]:
    """→ [(verb, template)] from every ``request("VERB", path)`` call
    in client.py; f-string placeholders become "*"."""
    tree = (src or _Sources()).tree(paths.client)
    if tree is None:
        return []
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "request"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)):
            continue
        verb = node.args[0].value
        expr = node.args[1]
        if isinstance(expr, ast.Constant) and isinstance(
            expr.value, str
        ):
            out.append((verb, expr.value))
        elif isinstance(expr, ast.JoinedStr):
            parts = []
            for val in expr.values:
                if isinstance(val, ast.Constant):
                    parts.append(str(val.value))
                else:
                    parts.append("*")
            out.append((verb, "".join(parts)))
    return out


def _segments(template: str) -> list[str]:
    segs = [s for s in template.strip("/").split("/") if s]
    # A placeholder glued to text ("shard*" from f"/shard{i}") still
    # counts as one wildcard segment.
    return ["*" if "*" in s else s for s in segs]


def _client_matches(server_segs, client_segs) -> bool:
    """Server "*" matches exactly one segment; client "*" matches one
    OR MORE (``f"/{self.service_path}/{name}"`` covers nested service
    paths like ``dataset/csv``)."""

    def match(i: int, j: int) -> bool:
        if i == len(server_segs) and j == len(client_segs):
            return True
        if i == len(server_segs) or j == len(client_segs):
            return False
        s, c = server_segs[i], client_segs[j]
        if c == "*":
            # one-or-more server segments
            return any(
                match(k, j + 1)
                for k in range(i + 1, len(server_segs) + 1)
            )
        if s == "*":
            return match(i + 1, j + 1)
        return s == c and match(i + 1, j + 1)

    return match(0, 0)


def check_routes(
    paths: DriftPaths, src: "_Sources | None" = None
) -> list[Finding]:
    src = src or _Sources()
    findings: list[Finding] = []
    clients = [
        (verb, _segments(tpl))
        for verb, tpl in client_templates(paths, src)
    ]
    for verb, template, line in server_routes(paths, src):
        segs = _segments(template)
        if not any(
            cv == verb and _client_matches(segs, cseg)
            for cv, cseg in clients
        ):
            findings.append(Finding(
                str(paths.server), line, "route-missing-client",
                f"{verb} {template} has no client.py binding — the "
                "uniform REST surface promises one per route",
            ))
    # The dynamic every-route-metered gate must stay in the suite: it
    # is what guarantees new routes get metrics without a listing.
    obs_test = paths.tests_dir / "test_obs.py"
    text = src.text(obs_test)
    if (
        "test_every_registered_route_is_metered" not in text
        or "router.routes" not in text
    ):
        findings.append(Finding(
            str(obs_test), 1, "route-gate-missing",
            "tests/test_obs.py no longer carries the every-route-"
            "metered gate over server.router.routes",
        ))
    return findings


# -- metric families ---------------------------------------------------------


def _families_in_tree(tree: ast.Module) -> set[str]:
    """Family names created by this tree: registry ``counter/gauge/
    histogram(name, ...)`` calls, ``Counter/Gauge/Histogram(name,
    ...)`` constructors, and collector ``Family(kind, name, ...)``
    records (name is the SECOND positional there)."""
    fams: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (
            fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else ""
        )
        if name in ("counter", "gauge", "histogram",
                    "Counter", "Gauge", "Histogram"):
            arg_idx = 0
        elif name == "Family":
            arg_idx = 1
        else:
            continue
        if len(node.args) > arg_idx and isinstance(
            node.args[arg_idx], ast.Constant
        ) and isinstance(node.args[arg_idx].value, str):
            value = node.args[arg_idx].value
            if value.startswith("lo_"):
                fams.add(value)
    return fams


def registered_families(
    paths: DriftPaths, src: "_Sources | None" = None
) -> set[str]:
    src = src or _Sources()
    fams: set[str] = set()
    for p in _package_files(paths):
        tree = src.tree(p)
        if tree is not None:
            fams |= _families_in_tree(tree)
    return fams


def _local_families(tree: ast.Module) -> set[str]:
    return _families_in_tree(tree)


def _family_known(token: str, known: set[str]) -> bool:
    if token in known or token in _FAMILY_IGNORE:
        return True
    for suffix in _PROM_SUFFIXES:
        if token.endswith(suffix) and token[: -len(suffix)] in known:
            return True
    # Prefix mention ("lo_program_" startswith-style assertions).
    if token.endswith("_"):
        return any(fam.startswith(token) for fam in known)
    return False


def check_metrics(
    paths: DriftPaths, src: "_Sources | None" = None
) -> list[Finding]:
    src = src or _Sources()
    known = registered_families(paths, src)
    findings: list[Finding] = []
    for p in sorted(paths.tests_dir.glob("test_*.py")):
        tree = src.tree(p)
        if tree is None:
            continue
        local = _local_families(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            for m in _FAMILY_RE.finditer(node.value):
                tok = m.group(0)
                if not _family_known(tok, known | local):
                    findings.append(Finding(
                        str(p), node.lineno, "metric-unregistered",
                        f"{tok!r} looks like a metric family but no "
                        "registry call creates it",
                    ))
    readme_text = src.text(paths.readme)
    for m in _FAMILY_RE.finditer(readme_text):
        tok = m.group(0)
        if not _family_known(tok, known):
            line = readme_text.count("\n", 0, m.start()) + 1
            findings.append(Finding(
                str(paths.readme), line, "metric-unregistered",
                f"{tok!r} is documented in the README but no "
                "registry call creates it",
            ))
    return findings


def analyze_drift(paths: DriftPaths) -> list[Finding]:
    src = _Sources()  # one read+parse per artifact across all gates
    findings: list[Finding] = []
    findings += check_knobs(paths, src)
    findings += check_fault_points(paths, src)
    findings += check_routes(paths, src)
    findings += check_metrics(paths, src)
    return findings
