"""Orchestrates the lochecks analyzer families over a tree.

``run_checks(package_root)`` parses every package module once, runs
the per-module analyzers (concurrency, JAX hazards, cancellation) and
the cross-artifact drift gates, applies inline suppressions, and
returns a :class:`Report`.  ``scripts/lo_check.py`` is the CLI;
``tests/test_lochecks.py::test_package_is_clean`` is the tier-1 gate.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from .cancellation import analyze_cancellation
from .concurrency import analyze_concurrency
from .drift import DriftPaths, analyze_drift
from .findings import ERROR, WARN, Finding, apply_suppressions
from .jaxlint import analyze_jax

#: rule id -> one-line description (the README catalog is generated
#: from the same table the CLI prints with --rules).
RULES = {
    "lock-order": (
        ERROR,
        "inconsistent lock-acquisition order across methods "
        "(deadlock potential)",
    ),
    "lock-self-deadlock": (
        ERROR,
        "re-acquiring a held non-reentrant threading.Lock on the "
        "same path",
    ),
    "unlocked-shared-write": (
        ERROR,
        "shared instance state written both under a lock and bare, "
        "or bare across threads",
    ),
    "jit-host-sync": (
        ERROR,
        "host-device sync construct inside a jit/pjit-compiled body",
    ),
    "jit-mutable-global": (
        ERROR,
        "module-level mutable captured (frozen) at trace time inside "
        "a jitted body",
    ),
    "jit-shape-branch": (
        WARN,
        "Python branch on a traced argument's shape inside a jitted "
        "body (retraces per shape class)",
    ),
    "loop-no-cancel-check": (
        ERROR,
        "long-running loop never consults a cancel token / watchdog "
        "deadline (cooperative cancellation is the contract now)",
    ),
    "lock-order-global": (
        ERROR,
        "cross-module lock-order cycle in the composed whole-program "
        "graph (each module individually consistent)",
    ),
    "blocking-call-under-lock": (
        ERROR,
        "indefinitely-blocking call (join/wait/get/result/sleep/"
        "urlopen/subprocess without timeout) while holding a lock",
    ),
    "lock-name-mismatch": (
        ERROR,
        "concurrency_rt.make_lock name differs from the lock's "
        "static identity (witness edges would not line up)",
    ),
    "witness-unmatched-edge": (
        ERROR,
        "runtime-witnessed lock order missing from the static "
        "whole-program graph (static false negative)",
    ),
    "knob-missing-config": (
        ERROR, "LO_TPU_* knob absent from config.py",
    ),
    "knob-missing-compose": (
        ERROR, "LO_TPU_* knob absent from deploy/docker-compose.yml",
    ),
    "knob-missing-k8s": (
        ERROR, "LO_TPU_* knob absent from deploy/k8s.yaml",
    ),
    "knob-missing-readme": (
        ERROR, "LO_TPU_* knob absent from the README knob tables",
    ),
    "knob-unknown": (
        ERROR, "manifest/README knob that no code reads",
    ),
    "fault-point-unknown": (
        ERROR, "fault-point name faults/plane.py never registers",
    ),
    "route-missing-client": (
        ERROR, "REST route without a client.py binding",
    ),
    "route-gate-missing": (
        ERROR, "the every-route-metered test gate is gone",
    ),
    "metric-unregistered": (
        ERROR, "metric family used in tests/README but never "
        "registered",
    ),
}


@dataclasses.dataclass
class Report:
    findings: list  # unsuppressed, sorted
    suppressed: list
    files_scanned: int
    parse_errors: list  # [(path, message)]

    @property
    def errors(self) -> list:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> list:
        return [f for f in self.findings if f.severity == WARN]

    def exit_code(self) -> int:
        return 1 if (self.errors or self.parse_errors) else 0


def _dedupe(findings: list[Finding]) -> list[Finding]:
    seen: set = set()
    out = []
    for f in findings:
        key = (f.file, f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def run_checks(
    package_root: str | Path,
    *,
    repo_root: str | Path | None = None,
    drift: bool = True,
    whole_program: bool = False,
    witness_dump: str | Path | None = None,
) -> Report:
    """Run every analyzer family over ``package_root``.

    ``repo_root`` locates the cross-artifact surfaces (deploy
    manifests, README, tests); default: the package root's parent.
    ``drift=False`` runs only the per-module analyzers — what the
    golden tests use on synthetic fixture trees.
    ``whole_program=True`` additionally composes the per-module lock
    models into the global graph (cross-module inversions,
    blocking-call-under-lock, make_lock name congruence), and
    ``witness_dump`` cross-checks a runtime witness snapshot
    (``LO_TPU_WITNESS_DUMP`` JSON) against that graph.
    """
    package_root = Path(package_root)
    repo_root = Path(
        repo_root if repo_root is not None else package_root.parent
    )
    findings: list[Finding] = []
    texts: dict[str, str] = {}
    trees: dict[str, ast.Module] = {}
    parse_errors: list = []
    files = [
        p for p in sorted(package_root.rglob("*.py"))
        if "__pycache__" not in p.parts
    ]
    for path in files:
        text = path.read_text()
        texts[str(path)] = text
        try:
            tree = ast.parse(text)
        except SyntaxError as exc:
            parse_errors.append((str(path), str(exc)))
            continue
        trees[str(path)] = tree
        findings += analyze_concurrency(str(path), tree)
        findings += analyze_jax(str(path), tree)
        findings += analyze_cancellation(str(path), tree, text)
    if whole_program:
        from .wholeprogram import analyze_wholeprogram

        wp_findings, graph = analyze_wholeprogram(
            package_root, trees
        )
        findings += wp_findings
        if witness_dump is not None:
            from .witness import cross_check, load_dump

            findings += cross_check(load_dump(witness_dump), graph)
    if drift:
        paths = DriftPaths.for_repo(repo_root)
        drift_findings = analyze_drift(paths)
        for f in drift_findings:
            if f.file not in texts:
                try:
                    texts[f.file] = Path(f.file).read_text()
                except OSError:
                    pass
        findings += drift_findings
    kept, suppressed = apply_suppressions(_dedupe(findings), texts)
    kept.sort(key=lambda f: (f.file, f.line, f.rule))
    return Report(
        findings=kept,
        suppressed=suppressed,
        files_scanned=len(files),
        parse_errors=parse_errors,
    )
