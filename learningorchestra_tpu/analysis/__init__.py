"""First-party static-analysis suite (``lochecks``).

Three analyzer families over the package's own invariants:

- **Concurrency** (:mod:`.concurrency`): lock-acquisition order
  cycles, self-deadlocks, and inconsistently-locked shared state,
  modeled on the repo's idioms (``with self._lock:``, daemon threads,
  module-level registry locks, ``*_locked`` caller-holds-lock
  helpers).
- **JAX hazards** (:mod:`.jaxlint`): host-sync constructs, mutable-
  global capture, and shape-branching inside jit/pjit-compiled
  bodies; plus the cooperative-cancellation worklist rule
  (:mod:`.cancellation`).
- **Drift gates** (:mod:`.drift`): every ``LO_TPU_*`` knob, REST
  route, metric family, and fault point cross-checked against
  config.py, the deploy manifests, the README tables, client.py and
  faults/plane.py.
- **Whole-program** (:mod:`.wholeprogram`): the per-module lock
  models composed into one global lock-order graph across modules —
  cross-module inversion cycles, blocking-call-under-lock, and
  ``make_lock`` name congruence.
- **Witness cross-check** (:mod:`.witness`): runtime-observed lock
  orders (``concurrency_rt``, ``LO_TPU_WITNESS=1``) validated
  against the static graph; a witnessed edge the model lacks is a
  build-failing static false negative.

Run via ``python scripts/lo_check.py learningorchestra_tpu/
--whole-program`` or :func:`run_checks`; the tier-1 gate is
``tests/test_lochecks.py::test_package_is_clean``.
"""

from .drift import DriftPaths, analyze_drift
from .findings import ERROR, WARN, Finding
from .runner import RULES, Report, run_checks
from .witness import cross_check
from .wholeprogram import GlobalLockGraph, global_graph

__all__ = [
    "DriftPaths",
    "ERROR",
    "Finding",
    "GlobalLockGraph",
    "RULES",
    "Report",
    "WARN",
    "analyze_drift",
    "cross_check",
    "global_graph",
    "run_checks",
]
