"""Whole-program concurrency analysis.

The per-module checker (:mod:`.concurrency`) stops at file boundaries,
but the hazards that matter — engine ↔ leases ↔ compile-cache ↔ fleet —
span modules: a ``ReplicaSet`` method holding its own lock calls into
the ``DeviceLeaser``, which takes the lease condition, which the engine
watchdog also reaches from under the engine lock.  This pass composes
every module's lock model into ONE global lock-order graph by resolving
lock identities and call chains across modules, then checks three
whole-program rules:

``lock-order-global``
    A cycle in the global graph whose locks live in more than one
    module (single-module cycles are the per-module checker's
    jurisdiction).  Cross-module resolution covers: imported module
    functions (including package ``__init__`` re-exports), class
    constructors assigned to ``self.<attr>`` / module globals / local
    variables, and singleton accessors (``get_registry()``-style
    functions whose return resolves to a class instance).

``blocking-call-under-lock``
    A call that can block indefinitely — ``join()``/``wait()``/
    ``Future.result()``/queue ``get()`` without a timeout,
    ``time.sleep``, ``urlopen`` without a timeout, socket ops,
    ``subprocess`` waits — made while holding a lock (directly, or
    inside a ``*_locked`` helper whose every call site holds one).
    This is the exact shape of an unbounded drain hang: every other
    contender of that lock stalls behind the blocked holder.

``lock-name-mismatch``
    A ``concurrency_rt.make_lock/make_rlock/make_condition`` name
    argument that does not equal the lock's static identity
    (``Class.attr`` for instance locks, ``module.var`` for module
    globals).  The runtime witness records edges under these names;
    a mismatch would silently decouple the observed graph from the
    static one and blind the ``witness-unmatched-edge`` gate.

Known model limits (documented, deliberate): identity is TYPE-level
(two instances of one class share a lock name — per-instance ordering
like router fan-out across sibling batchers is out of scope), class
names are assumed unique package-wide, and attribute types come from
constructor-call assignments (an attribute wired later by another
component is invisible).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

from .concurrency import (
    _INIT_EXEMPT,
    _ModuleScan,
    _RT_FACTORIES,
    _find_cycle,
    _is_foreign,
    _lock_context_exempt,
)
from .findings import Finding

#: Python-level names that never resolve to package callables — skips
#: pointless table probes for the dominant call shapes.
_BUILTINS = frozenset((
    "len", "range", "print", "sorted", "enumerate", "zip", "min",
    "max", "sum", "abs", "round", "isinstance", "issubclass", "getattr",
    "setattr", "hasattr", "repr", "str", "int", "float", "bool",
    "list", "dict", "set", "tuple", "frozenset", "type", "iter",
    "next", "map", "filter", "any", "all", "open", "vars", "id",
    "super", "format", "hash", "callable", "delattr", "divmod",
))


def _modbase(path: str) -> str:
    p = Path(path)
    return p.parent.name if p.stem == "__init__" else p.stem


@dataclasses.dataclass
class GlobalLockGraph:
    """The composed whole-program lock model."""

    #: every global lock name ("Class.attr" / "module.var")
    names: set
    #: name -> {name}: acquisition-order edges
    edges: dict
    #: (a, b) -> (path, line) sample site
    edge_sites: dict
    #: name -> defining module path
    lock_module: dict

    @property
    def edge_pairs(self) -> set:
        return {
            (a, b) for a, outs in self.edges.items() for b in outs
        }


class _Program:
    """Cross-module symbol/type resolution over the parsed package."""

    def __init__(self, package_root: Path, trees: dict):
        self.root = Path(package_root)
        self.pkgname = self.root.name
        self.scans: dict[str, _ModuleScan] = {}
        self.trees = dict(trees)
        self.by_dotted: dict[str, str] = {}  # dotted -> path
        self.dotted_of: dict[str, str] = {}  # path -> dotted
        for path, tree in trees.items():
            self.scans[path] = _ModuleScan(path, tree)
            rel = Path(path).relative_to(self.root)
            parts = list(rel.with_suffix("").parts)
            if parts and parts[-1] == "__init__":
                parts = parts[:-1]
            dotted = ".".join(parts)
            self.by_dotted[dotted] = path
            self.dotted_of[path] = dotted
        # local import name -> ("mod", dotted) | ("sym", dotted, name)
        self.imports: dict[str, dict] = {
            path: self._collect_imports(path) for path in trees
        }
        # classname -> (path, _ClassInfo); first definition wins.
        self.classes: dict[str, tuple] = {}
        for path, scan in self.scans.items():
            for cls in scan.classes.values():
                self.classes.setdefault(cls.name, (path, cls))
        self.self_attr_types = self._collect_self_attr_types()
        self.module_instance_types = {
            path: self._instance_types(path)
            for path in trees
        }
        self.method_ret = self._collect_method_returns()
        self.ret_class = self._collect_return_classes()
        self._local_type_cache: dict = {}

    # -- imports ---------------------------------------------------------

    def _collect_imports(self, path: str) -> dict:
        table: dict = {}
        dotted = self.dotted_of[path]
        pkg_parts = dotted.split(".") if dotted else []
        if Path(path).stem != "__init__" and pkg_parts:
            pkg_parts = pkg_parts[:-1]
        for node in ast.walk(self.trees[path]):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self._strip_pkg(alias.name)
                    if target is None:
                        continue
                    local = alias.asname or alias.name.split(".")[0]
                    if alias.asname is None and "." in alias.name:
                        # ``import pkg.a.b`` binds ``pkg`` — the root
                        # package; attribute chains through it are out
                        # of model.
                        continue
                    if target in self.by_dotted:
                        table[local] = ("mod", target)
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(node, pkg_parts)
                if base is None:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub = (
                        f"{base}.{alias.name}" if base else alias.name
                    )
                    if sub in self.by_dotted:
                        table[local] = ("mod", sub)
                    elif base in self.by_dotted or base == "":
                        table[local] = ("sym", base, alias.name)
        return table

    def _strip_pkg(self, dotted: str) -> str | None:
        if dotted == self.pkgname:
            return ""
        prefix = self.pkgname + "."
        if dotted.startswith(prefix):
            return dotted[len(prefix):]
        return None

    def _from_base(self, node: ast.ImportFrom, pkg_parts) -> str | None:
        if node.level == 0:
            return self._strip_pkg(node.module or "")
        parts = list(pkg_parts)
        for _ in range(node.level - 1):
            if not parts:
                return None
            parts.pop()
        if node.module:
            parts += node.module.split(".")
        return ".".join(parts)

    def resolve_symbol(self, dotted: str, name: str, depth: int = 0):
        """→ ("class", classname) | ("func", dotted, name) |
        ("mod", dotted) | None, following ``__init__`` re-exports."""
        if depth > 4:
            return None
        path = self.by_dotted.get(dotted)
        if path is None:
            return None
        scan = self.scans[path]
        if name in scan.classes:
            return ("class", name)
        if name in scan.module_units:
            return ("func", dotted, name)
        entry = self.imports[path].get(name)
        if entry is None:
            return None
        if entry[0] == "mod":
            return ("mod", entry[1])
        return self.resolve_symbol(entry[1], entry[2], depth + 1)

    # -- instance typing -------------------------------------------------

    def _constructor_class(self, path: str, call: ast.expr,
                           local_funcs: bool = False) -> str | None:
        """``ClassName(...)`` / ``mod.ClassName(...)`` → class name."""
        if not isinstance(call, ast.Call):
            return None
        fn = call.func
        scan = self.scans[path]
        if isinstance(fn, ast.Name):
            if fn.id in scan.classes:
                return fn.id
            entry = self.resolve_symbol(
                self.dotted_of[path], fn.id
            )
            if entry and entry[0] == "class":
                return entry[1]
        elif isinstance(fn, ast.Attribute) and isinstance(
            fn.value, ast.Name
        ):
            entry = self.imports[path].get(fn.value.id)
            if entry and entry[0] == "mod":
                target = self.resolve_symbol(entry[1], fn.attr)
                if target and target[0] == "class":
                    return target[1]
        return None

    def _annotation_class(self, ann) -> str | None:
        if isinstance(ann, ast.Name) and ann.id in self.classes:
            return ann.id
        if isinstance(ann, ast.Constant) and isinstance(
            ann.value, str
        ) and ann.value.strip("\"'") in self.classes:
            return ann.value.strip("\"'")
        if isinstance(ann, ast.Attribute) and ann.attr in self.classes:
            return ann.attr
        return None

    def _param_types(self, fn_node) -> dict:
        """Annotated parameters → class names (``registry:
        MetricsRegistry`` in ``_Metric.__init__``)."""
        types: dict = {}
        args = getattr(fn_node, "args", None)
        if args is None:
            return types
        for arg in (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
        ):
            cls = self._annotation_class(arg.annotation)
            if cls:
                types[arg.arg] = cls
        return types

    def _collect_self_attr_types(self) -> dict:
        out: dict = {}
        for path, scan in self.scans.items():
            for cls in scan.classes.values():
                types: dict = {}
                for unit in cls.units.values():
                    params = self._param_types(unit.node)
                    for node in ast.walk(unit.node):
                        if not isinstance(node, ast.Assign):
                            continue
                        made = self._constructor_class(
                            path, node.value
                        )
                        if made is None and isinstance(
                            node.value, ast.Name
                        ):
                            # ``self.registry = registry`` with an
                            # annotated parameter.
                            made = params.get(node.value.id)
                        if made is None:
                            continue
                        for tgt in node.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                types[tgt.attr] = made
                out.setdefault(cls.name, {}).update(types)
        return out

    def _collect_method_returns(self) -> dict:
        """(classname, method) -> classname from return annotations
        (``DocumentStore._get(...) -> _Collection``)."""
        out: dict = {}
        for _path, scan in self.scans.items():
            for cls in scan.classes.values():
                for name, unit in cls.units.items():
                    made = self._annotation_class(
                        getattr(unit.node, "returns", None)
                    )
                    if made:
                        out[(cls.name, name)] = made
        return out

    def _instance_types(self, path: str) -> dict:
        """name -> classname for ``x = ClassName(...)`` assignments
        anywhere in the module (module globals AND function locals —
        type-level overapproximation, same-name reuse merges)."""
        tree = self.trees[path]
        types: dict = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            made = self._constructor_class(path, node.value)
            if made is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    types[tgt.id] = made
        return types

    def _collect_return_classes(self) -> dict:
        """(dotted, funcname) -> classname for functions returning a
        known class instance (annotation, ``return <global-instance>``
        or ``return ClassName(...)``) — the ``get_registry()``
        singleton-accessor idiom."""
        out: dict = {}
        for path, scan in self.scans.items():
            dotted = self.dotted_of[path]
            inst = self.module_instance_types[path]
            for name, unit in scan.module_units.items():
                node = unit.node
                cls = None
                returns = getattr(node, "returns", None)
                if isinstance(returns, ast.Name) and (
                    returns.id in self.classes
                ):
                    cls = returns.id
                elif isinstance(returns, ast.Constant) and isinstance(
                    returns.value, str
                ) and returns.value in self.classes:
                    cls = returns.value
                if cls is None:
                    for sub in ast.walk(node):
                        if not isinstance(sub, ast.Return):
                            continue
                        if isinstance(sub.value, ast.Name):
                            cls = inst.get(sub.value.id)
                        else:
                            cls = self._constructor_class(
                                path, sub.value
                            )
                        if cls:
                            break
                if cls:
                    out[(dotted, name)] = cls
        return out

    # -- per-unit local typing -------------------------------------------

    def local_types(self, path: str, cls_name: str | None,
                    unit) -> dict:
        """var -> classname inside one callable: annotated params,
        constructor calls, typed accessor returns (``coll =
        self._get(...)``), and self-attr aliases (``reg =
        self.registry``)."""
        key = (path, cls_name, unit.name)
        cached = self._local_type_cache.get(key)
        if cached is not None:
            return cached
        types = self._param_types(unit.node)
        dotted = self.dotted_of[path]
        for node in ast.walk(unit.node):
            if not isinstance(node, ast.Assign):
                continue
            made = self._constructor_class(path, node.value)
            value = node.value
            if made is None and isinstance(value, ast.Call):
                fn = value.func
                if (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "self"
                    and cls_name is not None
                ):
                    made = self.method_ret.get((cls_name, fn.attr))
                elif isinstance(fn, ast.Name):
                    made = self.ret_class.get((dotted, fn.id))
                    if made is None:
                        entry = self.imports[path].get(fn.id)
                        if entry and entry[0] == "sym":
                            resolved = self.resolve_symbol(
                                entry[1], entry[2]
                            )
                            if resolved and resolved[0] == "func":
                                made = self.ret_class.get(
                                    (resolved[1], resolved[2])
                                )
                elif isinstance(fn, ast.Attribute) and isinstance(
                    fn.value, ast.Name
                ):
                    # ``reg = obs_metrics.get_registry()`` — an
                    # imported module's typed accessor.
                    entry = self.imports[path].get(fn.value.id)
                    if entry and entry[0] == "mod":
                        made = self.ret_class.get(
                            (entry[1], fn.attr)
                        )
            if made is None and isinstance(value, ast.Attribute) and (
                isinstance(value.value, ast.Name)
                and value.value.id == "self"
                and cls_name is not None
            ):
                made = self.self_attr_types.get(
                    cls_name, {}
                ).get(value.attr)
            if made is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    types[tgt.id] = made
        self._local_type_cache[key] = types
        return types

    # -- lock-identity resolution ----------------------------------------

    def resolve_lock(self, scan: _ModuleScan, path: str,
                     cls_name: str | None, unit, key) -> str | None:
        """A per-module lock key (incl. foreign receivers) → global
        name, or None when the receiver's type is unresolvable."""
        owner, rest = key
        if owner == "<foreign>":
            var, attr = rest.split(".", 1)
            target = self.local_types(path, cls_name, unit).get(var)
        elif owner == "<foreignself>":
            attr2, attr = rest.split(".", 1)
            target = self.self_attr_types.get(
                cls_name or "", {}
            ).get(attr2)
        else:
            return _gname(scan, key)
        if target is None:
            return None
        entry = self.classes.get(target)
        if entry is None or attr not in entry[1].locks:
            return None
        return f"{target}.{attr}"

    # -- call-target resolution ------------------------------------------

    def resolve_call(self, path: str, cls_name: str | None,
                     kind: str, ref: str, method: str | None,
                     unit=None):
        """An ext_call record → callable node id
        ``(path, classname|None, unitname)`` or None."""
        dotted = self.dotted_of[path]
        if kind == "selfattr" and cls_name is not None:
            target_cls = self.self_attr_types.get(
                cls_name, {}
            ).get(ref)
            return self._class_method(target_cls, method)
        if kind == "name":
            if unit is not None:
                local = self.local_types(
                    path, cls_name, unit
                ).get(ref)
                if local is not None:
                    return self._class_method(local, method)
            entry = self.imports[path].get(ref)
            if entry is not None:
                if entry[0] == "mod":
                    mod_path = self.by_dotted.get(entry[1])
                    if mod_path and method in self.scans[
                        mod_path
                    ].module_units:
                        return (mod_path, None, method)
                    resolved = self.resolve_symbol(entry[1], method)
                    if resolved and resolved[0] == "func":
                        fpath = self.by_dotted.get(resolved[1])
                        if fpath and resolved[2] in self.scans[
                            fpath
                        ].module_units:
                            return (fpath, None, resolved[2])
                    return None
                resolved = self.resolve_symbol(entry[1], entry[2])
                if resolved and resolved[0] == "class":
                    return self._class_method(resolved[1], method)
                return None
            made = self.module_instance_types[path].get(ref)
            if made:
                return self._class_method(made, method)
            if ref in self.scans[path].classes:
                return self._class_method(ref, method)
            return None
        if kind == "callresult":
            target = None
            if ref in self.scans[path].module_units:
                target = self.ret_class.get((dotted, ref))
            else:
                entry = self.imports[path].get(ref)
                if entry and entry[0] == "sym":
                    resolved = self.resolve_symbol(
                        entry[1], entry[2]
                    )
                    if resolved and resolved[0] == "func":
                        target = self.ret_class.get(
                            (resolved[1], resolved[2])
                        )
            return self._class_method(target, method)
        if kind == "bare":
            if ref in _BUILTINS:
                return None
            if ref in self.scans[path].module_units:
                return (path, None, ref)
            entry = self.imports[path].get(ref)
            if entry and entry[0] == "sym":
                resolved = self.resolve_symbol(entry[1], entry[2])
                if resolved is None:
                    return None
                if resolved[0] == "func":
                    fpath = self.by_dotted.get(resolved[1])
                    if fpath and resolved[2] in self.scans[
                        fpath
                    ].module_units:
                        return (fpath, None, resolved[2])
                if resolved[0] == "class":
                    return self._class_method(
                        resolved[1], "__init__"
                    )
            if ref in self.scans[path].classes:
                return self._class_method(ref, "__init__")
        return None

    def _class_method(self, cls_name: str | None,
                      method: str | None):
        if cls_name is None or method is None:
            return None
        entry = self.classes.get(cls_name)
        if entry is None:
            return None
        path, info = entry
        if method in info.units:
            return (path, cls_name, method)
        return None


# -- global graph ------------------------------------------------------------


def _gname(scan: _ModuleScan, key) -> str:
    owner, attr = key
    if owner == "<module>":
        return f"{_modbase(scan.path)}.{attr}"
    return f"{owner}.{attr}"


def _build_graph(program: _Program) -> GlobalLockGraph:
    names: set = set()
    lock_module: dict = {}
    edges: dict = {}
    edge_sites: dict = {}

    # Lock inventory.
    for path, scan in program.scans.items():
        for var in scan.module_locks:
            name = _gname(scan, ("<module>", var))
            names.add(name)
            lock_module.setdefault(name, path)
        for cls in scan.classes.values():
            for attr in cls.locks:
                name = f"{cls.name}.{attr}"
                names.add(name)
                lock_module.setdefault(name, path)

    # Callable nodes + per-node direct acquires and call targets.
    nodes: dict = {}  # id -> (scan, cls|None, unit)
    for path, scan in program.scans.items():
        for name, unit in scan.module_units.items():
            nodes[(path, None, name)] = (scan, None, unit)
        for cls in scan.classes.values():
            for name, unit in cls.units.items():
                nodes[(path, cls.name, name)] = (scan, cls, unit)

    call_edges: dict = {nid: set() for nid in nodes}
    for nid, (scan, cls, unit) in nodes.items():
        path = nid[0]
        for _held, callee, _line in unit.self_calls:
            if cls is not None:
                for uname in cls.units:
                    if uname.split(".")[0] == callee:
                        call_edges[nid].add(
                            (path, cls.name, uname)
                        )
        for _held, kind, ref, method, _line in unit.ext_calls:
            target = program.resolve_call(
                path, cls.name if cls else None, kind, ref,
                method, unit=unit,
            )
            if target is not None and target in nodes:
                call_edges[nid].add(target)

    def lockname(nid, key) -> str | None:
        scan, cls, unit = nodes[nid]
        return program.resolve_lock(
            scan, nid[0], cls.name if cls else None, unit, key
        )

    # Transitive lock closure per callable (unresolvable foreign
    # receivers drop out — under-approximation the runtime witness
    # cross-check exists to catch).
    closure: dict = {}
    for nid, (scan, cls, unit) in nodes.items():
        mine = set()
        for key in unit.acquires:
            name = lockname(nid, key)
            if name is not None:
                mine.add(name)
        closure[nid] = mine
    changed = True
    while changed:
        changed = False
        for nid, callees in call_edges.items():
            mine = closure[nid]
            for callee in callees:
                extra = closure.get(callee)
                if extra and not extra <= mine:
                    mine |= extra
                    changed = True

    def add_edge(a: str | None, b: str | None, path: str,
                 line: int) -> None:
        if a is None or b is None or a == b:
            return
        edges.setdefault(a, set()).add(b)
        edge_sites.setdefault((a, b), (path, line))

    # Edge generation: direct nesting + held-across-call composition.
    for nid, (scan, cls, unit) in nodes.items():
        path = nid[0]
        for key, line, held in unit.acq_sites:
            for h in held:
                add_edge(
                    lockname(nid, h), lockname(nid, key), path, line
                )
        for held, callee, line in unit.self_calls:
            if not held or cls is None:
                continue
            for uname in cls.units:
                if uname.split(".")[0] != callee:
                    continue
                for lock in closure[(path, cls.name, uname)]:
                    for h in held:
                        add_edge(
                            lockname(nid, h), lock, path, line
                        )
        for held, kind, ref, method, line in unit.ext_calls:
            if not held:
                continue
            target = program.resolve_call(
                path, cls.name if cls else None, kind, ref,
                method, unit=unit,
            )
            if target is None or target not in closure:
                continue
            for lock in closure[target]:
                for h in held:
                    add_edge(lockname(nid, h), lock, path, line)

    return GlobalLockGraph(
        names=names, edges=edges, edge_sites=edge_sites,
        lock_module=lock_module,
    )


# -- rules -------------------------------------------------------------------


def _cycle_findings(graph: GlobalLockGraph) -> list:
    findings: list = []
    edges = {a: set(bs) for a, bs in graph.edges.items()}
    for _ in range(64):  # bounded: one edge removed per iteration
        cycle = _find_cycle(edges)
        if cycle is None:
            break
        pairs = list(zip(cycle, cycle[1:]))
        modules = {
            graph.lock_module.get(n) for n in cycle[:-1]
        }
        if len(modules) > 1:
            path, line = graph.edge_sites.get(
                pairs[0], ("<wholeprogram>", 1)
            )
            order = " -> ".join(cycle)
            findings.append(Finding(
                path, line, "lock-order-global",
                "cross-module lock-order cycle "
                f"({order}); each module is individually "
                "consistent but their composition can deadlock",
            ))
        a, b = pairs[0]
        edges.get(a, set()).discard(b)
    return findings


_QUEUEISH_RE = re.compile(r"(queue|^q$|_q$)", re.IGNORECASE)
_SOCKISH_RE = re.compile(r"(sock|conn)", re.IGNORECASE)
_FUTUREISH_RE = re.compile(r"(future|fut$)", re.IGNORECASE)


def _blocking_reason(name, n_args, kws, rkey, rname, held) -> str | None:
    """→ human reason when this call shape can block indefinitely."""
    has_timeout = "timeout" in kws
    if name == "sleep" and (rname is None or rname == "time"):
        return "time.sleep() stalls every contender of the held lock"
    if name == "join" and n_args == 0 and not has_timeout:
        return "join() without a timeout"
    if name == "wait" and n_args == 0 and not has_timeout:
        if rkey is not None and rkey in held:
            return None  # waiting on the held condition releases it
        return "wait() without a timeout"
    if (
        name == "get" and not has_timeout and n_args == 0
        and rname and _QUEUEISH_RE.search(rname)
    ):
        # Zero positional args: a dict-style ``.get(key)`` lookup on a
        # queue-named mapping is not the blocking ``Queue.get()``.
        return "queue get() without a timeout"
    if (
        name == "result" and n_args == 0 and not has_timeout
        and rname and _FUTUREISH_RE.search(rname)
    ):
        return "Future.result() without a timeout"
    if name == "urlopen" and not has_timeout and n_args < 3:
        return "urlopen() without a timeout"
    if (
        name in ("recv", "accept", "connect")
        and rname and _SOCKISH_RE.search(rname)
    ):
        return f"socket {name}() can block on the network"
    if (
        name in ("check_output", "check_call", "communicate")
        and not has_timeout
    ):
        return f"subprocess {name}() without a timeout"
    return None


def _blocking_findings(program: _Program) -> list:
    findings: list = []
    for path, scan in program.scans.items():
        scopes = [(None, scan.module_units)] + [
            (cls, cls.units) for cls in scan.classes.values()
        ]
        for cls, units in scopes:
            exempt = _lock_context_exempt(cls) if cls else set()
            for unit in units.values():
                base = unit.name.split(".")[0]
                ambient = (
                    unit.name in exempt
                    and base not in _INIT_EXEMPT
                    and cls is not None and cls.locks
                )
                for (held, name, n_args, kws, rkey, rname,
                     line) in unit.blocking_calls:
                    if not held and not ambient:
                        continue
                    reason = _blocking_reason(
                        name, n_args, kws, rkey, rname, held
                    )
                    if reason is None:
                        continue
                    held_names = [
                        program.resolve_lock(
                            scan, path,
                            cls.name if cls else None, unit, h,
                        ) or h[1]
                        for h in held
                    ] or [
                        f"{cls.name}.<caller-held "
                        f"{'/'.join(sorted(cls.locks))}>"
                    ]
                    findings.append(Finding(
                        path, line, "blocking-call-under-lock",
                        f"{unit.name} holds "
                        f"{', '.join(held_names)} across a blocking "
                        f"call: {reason} — every contender stalls "
                        "behind it (the unbounded-drain hang shape)",
                    ))
    return findings


def _lock_name_findings(program: _Program) -> list:
    findings: list = []
    for path, tree in program.trees.items():
        modbase = _modbase(path)

        class _Visitor(ast.NodeVisitor):
            def __init__(self):
                self.cls_stack: list = []
                self.fn_depth = 0

            def visit_ClassDef(self, node):
                self.cls_stack.append(node.name)
                self.generic_visit(node)
                self.cls_stack.pop()

            def _visit_fn(self, node):
                self.fn_depth += 1
                self.generic_visit(node)
                self.fn_depth -= 1

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn

            def visit_Assign(self, node):
                given = _rt_factory_arg(node.value)
                if given is not None:
                    for tgt in node.targets:
                        expected = self._expected(tgt)
                        if expected and given != expected:
                            findings.append(Finding(
                                path, node.lineno,
                                "lock-name-mismatch",
                                f"witness lock named {given!r} but "
                                f"its static identity is "
                                f"{expected!r} — observed edges "
                                "would not line up with the "
                                "whole-program graph",
                            ))
                self.generic_visit(node)

            def _expected(self, tgt) -> str | None:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and self.cls_stack
                ):
                    return f"{self.cls_stack[-1]}.{tgt.attr}"
                if isinstance(tgt, ast.Name):
                    if self.fn_depth:
                        return None  # local variable — unmodeled
                    if self.cls_stack:
                        return f"{self.cls_stack[-1]}.{tgt.id}"
                    return f"{modbase}.{tgt.id}"
                return None

        _Visitor().visit(tree)
    return findings


def _rt_factory_arg(node: ast.expr) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = (
        fn.attr if isinstance(fn, ast.Attribute)
        else fn.id if isinstance(fn, ast.Name) else None
    )
    if name in _RT_FACTORIES and node.args and isinstance(
        node.args[0], ast.Constant
    ) and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


# -- entry points ------------------------------------------------------------


def analyze_wholeprogram(
    package_root: str | Path, trees: dict
) -> tuple:
    """→ (findings, :class:`GlobalLockGraph`) over ``trees``
    (path → parsed module, as produced by the runner)."""
    program = _Program(Path(package_root), trees)
    graph = _build_graph(program)
    findings: list = []
    findings += _cycle_findings(graph)
    findings += _blocking_findings(program)
    findings += _lock_name_findings(program)
    return findings, graph


def global_graph(package_root: str | Path) -> GlobalLockGraph:
    """Parse ``package_root`` and build the global lock graph — the
    witness cross-check's static side (tests and the CLI use this
    without re-running the full rule set)."""
    package_root = Path(package_root)
    trees: dict = {}
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            trees[str(path)] = ast.parse(path.read_text())
        except (OSError, SyntaxError):
            continue
    return _build_graph(_Program(package_root, trees))
