"""JAX hazard lints for jit/pjit-compiled function bodies.

The compile cache's whole value proposition (train/compile_cache.py)
is "trace once, dispatch forever" — and the pjit/TPU scaling
literature is blunt that the difference between "fast once" and "fast
always" is keeping host sync and retraces out of the dispatch path.
These rules flag the constructs that break that contract *inside*
functions handed to ``jax.jit`` / ``pjit``:

``jit-host-sync`` (error)
    ``float()``/``int()``/``bool()`` on a traced value, ``.item()`` /
    ``.tolist()`` / ``.block_until_ready()``, ``np.asarray`` /
    ``np.array``, ``jax.device_get``: each one forces the host to wait
    on the device mid-trace (or burns a constant-fold), serializing
    dispatch.

``jit-mutable-global`` (error)
    Reading a module-level ``dict``/``list``/``set`` inside a jitted
    body captures a snapshot at trace time: mutations after the first
    call silently never apply (the cached executable keeps the old
    value) — the classic "why does my flag do nothing" bug.

``jit-shape-branch`` (warn)
    Python ``if``/``while`` on an argument's ``.shape``/``len()``
    retraces per shape class.  Sometimes intended (bucketing does
    exactly this, deliberately) — hence warn, not error.

Traced-value tracking is one-hop taint: the jitted function's
parameters are tainted, and any local assigned from an expression
mentioning a tainted name becomes tainted (fixpoint).
"""

from __future__ import annotations

import ast

from .findings import WARN, Finding

_JIT_NAMES = {"jit", "pjit"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_MODULES = {"np", "numpy", "onp"}
_MUTABLE_LITERALS = (
    ast.Dict, ast.List, ast.Set, ast.ListComp, ast.SetComp,
    ast.DictComp,
)


def _is_jit_callable(node: ast.expr) -> bool:
    """``jax.jit`` / ``jit`` / ``pjit`` / ``partial(jax.jit, ...)``."""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    if isinstance(node, ast.Call):
        fn = node.func
        is_partial = (
            isinstance(fn, ast.Name) and fn.id == "partial"
        ) or (isinstance(fn, ast.Attribute) and fn.attr == "partial")
        if is_partial and node.args:
            return _is_jit_callable(node.args[0])
        # jax.jit(fn, static_argnums=...) used as decorator factory —
        # the Call itself IS the jit application.
        return _is_jit_callable(fn)
    return False


def _collect_module_mutables(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if isinstance(value, _MUTABLE_LITERALS) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("dict", "list", "set")
        ):
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _function_map(tree: ast.AST) -> dict[int, ast.AST]:
    """Map id(FunctionDef/Lambda) for every def in the tree."""
    return {
        id(n): n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda))
    }


def _resolve_jitted(tree: ast.Module) -> list[ast.AST]:
    """All function nodes handed to jit/pjit: decorated defs, direct
    ``jax.jit(fn)`` / ``jax.jit(lambda ...)`` call sites with ``fn``
    a def visible in the enclosing body."""
    jitted: list[ast.AST] = []
    # Decorated defs.
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_jit_callable(deco):
                    jitted.append(node)
    # Call-form: jax.jit(target, ...).  Name targets resolve through
    # the enclosing lexical scopes, innermost first — the repo's
    # builders define the epoch fn a few lines above the jit call in
    # the same closure, and a same-named def in an unrelated scope
    # must NOT match.
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _is_jit_callable(node.func)
                and node.args):
            continue
        target = node.args[0]
        if isinstance(target, ast.Lambda):
            jitted.append(target)
        elif isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)):
            jitted.append(target)
        elif isinstance(target, ast.Name):
            scope: ast.AST | None = node
            while scope is not None:
                scope = parents.get(id(scope))
                if not isinstance(
                    scope,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module),
                ):
                    continue
                hit = next(
                    (
                        item for item in scope.body
                        if isinstance(
                            item,
                            (ast.FunctionDef, ast.AsyncFunctionDef),
                        )
                        and item.name == target.id
                    ),
                    None,
                )
                if hit is not None:
                    jitted.append(hit)
                    break
    # De-dup (a decorated def can also be re-wrapped).
    seen: set[int] = set()
    out = []
    for fn in jitted:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)
    return out


def _param_names(fn: ast.AST) -> set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args
             + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def _walk_own_scope(fn: ast.AST):
    """Walk ``fn``'s body, pruning nested def/lambda subtrees — their
    assignments bind in a DIFFERENT scope and must not leak into the
    outer function's analysis (``ast.walk`` has no pruning)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _tainted_names(fn: ast.AST) -> set[str]:
    """Params + locals assigned from expressions mentioning tainted
    names (fixpoint), in ``fn``'s own scope only."""
    tainted = _param_names(fn)
    changed = True
    while changed:
        changed = False
        for node in _walk_own_scope(fn):
            if not isinstance(node, ast.Assign):
                continue
            value_names = {
                n.id for n in ast.walk(node.value)
                if isinstance(n, ast.Name)
            }
            if not value_names & tainted:
                continue
            for tgt in node.targets:
                for leaf in ast.walk(tgt):
                    if (
                        isinstance(leaf, ast.Name)
                        and leaf.id not in tainted
                    ):
                        tainted.add(leaf.id)
                        changed = True
    return tainted


def _base_name(node: ast.expr) -> str | None:
    """Leftmost Name of an attribute/subscript/call chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = (
            node.func if isinstance(node, ast.Call) else node.value
        )
    return node.id if isinstance(node, ast.Name) else None


def _mentions_tainted(node: ast.expr, tainted: set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in tainted
        for n in ast.walk(node)
    )


def analyze_jax(path: str, tree: ast.Module) -> list[Finding]:
    findings: list[Finding] = []
    mutables = _collect_module_mutables(tree)

    for fn in _resolve_jitted(tree):
        tainted = _tainted_names(fn)
        fn_name = getattr(fn, "name", "<lambda>")
        body_nodes = (
            fn.body if isinstance(fn.body, list) else [fn.body]
        )
        local_stores = {
            n.id for n in _walk_own_scope(fn)
            if isinstance(n, ast.Name)
            and isinstance(n.ctx, ast.Store)
        }
        for stmt in body_nodes:
            for node in ast.walk(stmt):
                findings.extend(_check_node(
                    path, fn_name, node, tainted, mutables,
                    local_stores,
                ))
    return findings


def _check_node(path, fn_name, node, tainted, mutables,
                local_stores) -> list[Finding]:
    out: list[Finding] = []
    if isinstance(node, ast.Call):
        fn = node.func
        # float(x) / int(x) / bool(x) on a traced value.
        if (
            isinstance(fn, ast.Name)
            and fn.id in ("float", "int", "bool")
            and node.args
            and _mentions_tainted(node.args[0], tainted)
        ):
            out.append(Finding(
                path, node.lineno, "jit-host-sync",
                f"{fn.id}() on a traced value inside jitted "
                f"{fn_name}() blocks dispatch on a device "
                "round-trip (ConcretizationError at best, a silent "
                "sync at worst)",
            ))
        # .item() / .tolist() / .block_until_ready()
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _SYNC_METHODS
            and _mentions_tainted(fn.value, tainted)
        ):
            out.append(Finding(
                path, node.lineno, "jit-host-sync",
                f".{fn.attr}() inside jitted {fn_name}() forces a "
                "host-device sync on the dispatch path",
            ))
        # np.asarray / np.array on traced values; jax.device_get.
        if isinstance(fn, ast.Attribute):
            base = _base_name(fn)
            if (
                base in _NUMPY_MODULES
                and fn.attr in ("asarray", "array")
                and node.args
                and _mentions_tainted(node.args[0], tainted)
            ):
                out.append(Finding(
                    path, node.lineno, "jit-host-sync",
                    f"{base}.{fn.attr}() on a traced value inside "
                    f"jitted {fn_name}() pulls the array to host "
                    "memory mid-trace",
                ))
            if fn.attr == "device_get":
                out.append(Finding(
                    path, node.lineno, "jit-host-sync",
                    f"jax.device_get inside jitted {fn_name}() is a "
                    "synchronous device->host transfer",
                ))
    elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
        if node.id in mutables and node.id not in local_stores \
                and node.id not in tainted:
            out.append(Finding(
                path, node.lineno, "jit-mutable-global",
                f"jitted {fn_name}() reads module-level mutable "
                f"{node.id!r}: its value is captured at trace time — "
                "later mutations never reach the cached executable",
            ))
    elif isinstance(node, (ast.If, ast.While)):
        test = node.test
        shapeish = any(
            (isinstance(n, ast.Attribute) and n.attr in
             ("shape", "ndim", "size")
             and _mentions_tainted(n.value, tainted))
            or (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "len"
                and n.args
                and _mentions_tainted(n.args[0], tainted))
            for n in ast.walk(test)
        )
        if shapeish:
            out.append(Finding(
                path, node.lineno, "jit-shape-branch",
                f"Python branch on a traced argument's shape inside "
                f"jitted {fn_name}() retraces per shape class "
                "(deliberate bucketing should suppress this)",
                severity=WARN,
            ))
    return out
