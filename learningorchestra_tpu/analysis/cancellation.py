"""Cooperative-cancellation rule (error-level).

The job engine's deadline watchdog (jobs/engine.py) fails overdue jobs
and reclaims their worker slot and chip leases — but the job BODY
keeps running until it finishes on its own: Python threads cannot be
killed.  True cancellation needs the body to poll a cancel token, and
since the cancellation PR landed one (``jobs/cancel.py`` —
``cancel_requested()`` bound per dispatch, flipped by the watchdog and
the bounded shutdown drain), consulting it is the CONTRACT, not a
worklist item.

``loop-no-cancel-check`` flags long-running loop shapes inside the
job-execution and serving planes that never consult a stop/deadline
signal: ``while True:`` loops and epoch-style ``for`` loops whose body
neither touches an ``Event`` / deadline / cancel construct nor raises
out.  Error severity: the shutdown-drain hang this rule originally
named (the pre-cancellation ``JobEngine.shutdown``) is exactly what an
unchecked loop costs; suppress deliberate cases inline.
"""

from __future__ import annotations

import ast
import re

from .findings import ERROR, Finding

#: Only the planes where a runaway body holds real resources.
SCOPE_RE = re.compile(
    r"(jobs/|services/executor|train/neural|train/checkpoint"
    r"|parallel/(distributed|coordinator)|serve/)"
)

#: A loop consulting any of these is cooperating.
_CANCEL_TOKENS = re.compile(
    r"deadline|cancel|stop|shutdown|closed|is_set|wait\(|expired"
    r"|_shutting_down|should_|alive",
    re.IGNORECASE,
)


def _loop_source(node: ast.AST, lines: list[str]) -> str:
    end = getattr(node, "end_lineno", node.lineno)
    return "\n".join(lines[node.lineno - 1:end])


def _is_epoch_for(node: ast.For) -> bool:
    names = {
        n.id for n in ast.walk(node.target)
        if isinstance(n, ast.Name)
    }
    return any("epoch" in name.lower() for name in names)


def analyze_cancellation(path: str, tree: ast.Module,
                         text: str) -> list[Finding]:
    if not SCOPE_RE.search(path.replace("\\", "/")):
        return []
    lines = text.splitlines()
    findings: list[Finding] = []
    for node in ast.walk(tree):
        # Only shapes that can run LONG: ``while True`` (daemon/body
        # loops) and epoch-style fits.  A bounded arithmetic while
        # (``while b < rows: b <<= 1``) is not a cancellation concern.
        unbounded = (
            isinstance(node, ast.While)
            and isinstance(node.test, ast.Constant)
            and node.test.value is True
        )
        epochish = isinstance(node, ast.For) and _is_epoch_for(node)
        if not (unbounded or epochish):
            continue
        src = _loop_source(node, lines)
        if _CANCEL_TOKENS.search(src):
            continue
        shape = (
            "while-loop" if unbounded else "epoch for-loop"
        )
        findings.append(Finding(
            path, node.lineno, "loop-no-cancel-check",
            f"{shape} never consults a cancel token / watchdog "
            "deadline — the engine can fail the job but this body "
            "runs to completion (poll jobs/cancel.py's "
            "cancel_requested() between units of work)",
            severity=ERROR,
        ))
    return findings
