"""Lock-discipline & shared-state checker.

Models the repo's concurrency idioms — ``threading.Lock/RLock/
Condition`` attributes acquired with ``with self._lock:``, module-level
registry locks, daemon threads spawned via ``threading.Thread(
target=self._loop)`` — and enforces two invariants statically:

``lock-order``
    Within one module, the union of every method's lock-acquisition
    nestings (direct ``with`` nesting plus self-call propagation:
    holding A while calling ``self.m()`` which acquires B is an A→B
    edge) must form a DAG.  A cycle is deadlock potential: two threads
    entering the cycle from different methods can each hold the lock
    the other needs.

``lock-self-deadlock``
    Acquiring a non-reentrant ``threading.Lock`` that is already held
    on the same path (lexically nested ``with``, or a self-call whose
    callee re-acquires) deadlocks unconditionally the moment the path
    executes.

``unlocked-shared-write``
    An instance attribute written under a lock in one method and
    written bare in another is shared mutable state with inconsistent
    locking — exactly the torn-state class of bug the job engine /
    autoscaler / batcher daemons can hit.  Private helpers whose every
    intraclass call site holds a lock are exempt (the caller provides
    the critical section); ``__init__``-family methods are exempt
    (no concurrent alias exists yet); thread-target methods never are.
"""

from __future__ import annotations

import ast
import dataclasses

from .findings import Finding

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
#: The witnessed factories (concurrency_rt): first-party locks are
#: constructed through these, carrying their static identity as the
#: name argument (the whole-program pass checks the congruence).
_RT_FACTORIES = {
    "make_lock": "Lock",
    "make_rlock": "RLock",
    "make_condition": "Condition",
}
_REENTRANT = {"RLock"}
_INIT_EXEMPT = {
    "__init__", "__new__", "__post_init__", "__init_subclass__",
    "__set_name__",
}


def _lock_factory_name(node: ast.expr) -> str | None:
    """``threading.Lock()`` / ``Lock()`` / ``make_lock("...")`` →
    ``"Lock"`` (else None)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = (
        fn.attr if isinstance(fn, ast.Attribute)
        else fn.id if isinstance(fn, ast.Name) else None
    )
    if name in _RT_FACTORIES and node.args:
        return _RT_FACTORIES[name]
    if node.args or node.keywords:
        return None
    if name in _LOCK_FACTORIES:
        return name
    return None


def _foreign_key(expr: ast.expr):
    """``coll.lock`` / ``self.registry.lock`` → a foreign-lock key
    (("<foreign>", "var.attr") / ("<foreignself>", "attr2.attr")), or
    None.  Per-module rules treat these as opaque held context; the
    whole-program pass resolves the receiver's type."""
    if not isinstance(expr, ast.Attribute):
        return None
    attr = expr.attr
    if not ("lock" in attr.lower() or attr in ("_cv", "_cond")):
        return None
    base = expr.value
    if isinstance(base, ast.Name) and base.id != "self":
        return ("<foreign>", f"{base.id}.{attr}")
    if (
        isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id == "self"
    ):
        return ("<foreignself>", f"{base.attr}.{attr}")
    return None


def _is_foreign(key) -> bool:
    return key[0] in ("<foreign>", "<foreignself>")


@dataclasses.dataclass
class _Unit:
    """One analyzed callable: a method, module function, or nested def
    (a closure runs on its own stack — held locks don't flow in)."""

    name: str
    node: ast.AST
    cls: str | None
    acquires: set = dataclasses.field(default_factory=set)
    # (held_frozenset, callee_method_name, line)
    self_calls: list = dataclasses.field(default_factory=list)
    # (attr, line, held_frozenset)
    writes: list = dataclasses.field(default_factory=list)
    # lock_key -> [(line, held_before)]
    acq_sites: list = dataclasses.field(default_factory=list)
    # Cross-object calls, for the whole-program pass
    # (analysis/wholeprogram.py):
    # (held_tuple, kind, ref, method, line) with kind in
    # {"selfattr", "name", "callresult", "bare"}.
    ext_calls: list = dataclasses.field(default_factory=list)
    # Potentially-blocking calls made while holding locks:
    # (held_tuple, fn_name, n_args, kw_names, receiver_lock_key,
    #  receiver_name, line).
    blocking_calls: list = dataclasses.field(default_factory=list)


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.locks: dict[str, str] = {}  # attr -> factory
        self.units: dict[str, _Unit] = {}
        self.thread_targets: set[str] = set()


class _ModuleScan:
    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.module_locks: dict[str, str] = {}
        self.classes: dict[str, _ClassInfo] = {}
        self.module_units: dict[str, _Unit] = {}
        self._collect(tree)

    # -- collection ------------------------------------------------------

    def _collect(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign):
                factory = _lock_factory_name(node.value)
                if factory:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.module_locks[tgt.id] = factory
            elif isinstance(node, ast.ClassDef):
                self._collect_class(node)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                unit = _Unit(node.name, node, None)
                self.module_units[node.name] = unit
        # Lock attrs must be known before walking bodies, so walk in a
        # second pass.
        for cls in self.classes.values():
            for unit in list(cls.units.values()):
                _BodyWalker(self, cls, unit).walk()
        for unit in list(self.module_units.values()):
            _BodyWalker(self, None, unit).walk()

    def _collect_class(self, node: ast.ClassDef) -> None:
        info = _ClassInfo(node.name)
        for item in node.body:
            if isinstance(item, ast.Assign):
                factory = _lock_factory_name(item.value)
                if factory:
                    for tgt in item.targets:
                        if isinstance(tgt, ast.Name):
                            info.locks[tgt.id] = factory
            elif isinstance(
                item, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                info.units[item.name] = _Unit(
                    item.name, item, node.name
                )
                # self.X = threading.Lock() assignments anywhere.
                for sub in ast.walk(item):
                    if isinstance(sub, ast.Assign):
                        factory = _lock_factory_name(sub.value)
                        if factory:
                            for tgt in sub.targets:
                                if (
                                    isinstance(tgt, ast.Attribute)
                                    and isinstance(
                                        tgt.value, ast.Name
                                    )
                                    and tgt.value.id == "self"
                                ):
                                    info.locks[tgt.attr] = factory
        self.classes[node.name] = info

    # -- lock identity ---------------------------------------------------

    def lock_key(self, cls: _ClassInfo | None, expr: ast.expr):
        """``self._lock`` / module ``_LOCK`` → a stable key, or None."""
        if (
            cls is not None
            and isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in cls.locks
        ):
            return (cls.name, expr.attr)
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return ("<module>", expr.id)
        return None

    def is_reentrant(self, key) -> bool:
        owner, name = key
        if _is_foreign(key):
            # Unknown type → unknown reentrancy: treat as reentrant so
            # no per-module self-deadlock fires on a foreign key.
            return True
        if owner == "<module>":
            return self.module_locks.get(name) in _REENTRANT
        cls = self.classes.get(owner)
        return bool(cls) and cls.locks.get(name) in _REENTRANT


class _BodyWalker:
    """Walks one unit's statements tracking the held-lock stack."""

    def __init__(self, scan: _ModuleScan, cls, unit: _Unit):
        self.scan = scan
        self.cls = cls
        self.unit = unit

    def walk(self) -> None:
        body = getattr(self.unit.node, "body", [])
        self._walk_stmts(body, [])

    def _walk_stmts(self, stmts, held: list) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt: ast.stmt, held: list) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Closure: runs later, on its own stack.  Analyze as a
            # sibling unit (nested name) with an empty held set.
            nested = _Unit(
                f"{self.unit.name}.<{stmt.name}>", stmt,
                self.cls.name if self.cls else None,
            )
            owner = (
                self.cls.units if self.cls else self.scan.module_units
            )
            owner[nested.name] = nested
            _BodyWalker(self.scan, self.cls, nested).walk()
            return
        if isinstance(stmt, ast.ClassDef):
            return  # method-local classes: out of scope
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired_here = []
            for item in stmt.items:
                key = self.scan.lock_key(self.cls, item.context_expr)
                if key is None:
                    # ``with coll.lock:`` / ``with self.registry.lock:``
                    # — ANOTHER object's lock.  Identity needs cross-
                    # module typing, so the per-module rules skip these
                    # keys; the whole-program pass resolves them.
                    key = _foreign_key(item.context_expr)
                if key is not None:
                    # ``with self._a, self._b:`` acquires in item
                    # order — earlier items count as held for later
                    # ones.
                    self.unit.acquires.add(key)
                    self.unit.acq_sites.append(
                        (key, stmt.lineno, tuple(held + acquired_here))
                    )
                    acquired_here.append(key)
                else:
                    self._visit_subtree(item.context_expr, held)
            self._walk_stmts(stmt.body, held + acquired_here)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._visit_subtree(stmt.test, held)
            self._walk_stmts(stmt.body, held)
            self._walk_stmts(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_subtree(stmt.iter, held)
            self._walk_stmts(stmt.body, held)
            self._walk_stmts(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._walk_stmts(stmt.body, held)
            for handler in stmt.handlers:
                self._walk_stmts(handler.body, held)
            self._walk_stmts(stmt.orelse, held)
            self._walk_stmts(stmt.finalbody, held)
            return
        if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            self._visit_subtree(stmt.subject, held)
            for case in stmt.cases:
                self._walk_stmts(case.body, held)
            return
        # Simple statement: visit every expression node underneath.
        self._visit_subtree(stmt, held)

    def _visit_subtree(self, node: ast.AST, held: list) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # lambdas stay; real defs handled above
            self._visit_expr(sub, held)

    def _visit_expr(self, node: ast.AST, held: list) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return  # handled structurally
        if isinstance(node, ast.Call):
            self._visit_call(node, held)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AugAssign)
                else node.targets
            )
            for tgt in self._flatten_targets(targets):
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    self.unit.writes.append(
                        (tgt.attr, node.lineno, tuple(held))
                    )

    def _note_blocking(self, node: ast.Call, held: list) -> None:
        """Record a possibly-indefinitely-blocking call made while
        holding locks; the whole-program pass decides which shapes
        (no timeout argument, receiver kind) are findings."""
        fn = node.func
        name = (
            fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in self._BLOCKING_NAMES:
            return
        receiver_key = receiver_name = None
        if isinstance(fn, ast.Attribute):
            receiver_key = self.scan.lock_key(self.cls, fn.value)
            base = fn.value
            if isinstance(base, ast.Name):
                receiver_name = base.id
            elif isinstance(base, ast.Attribute):
                receiver_name = base.attr
        self.unit.blocking_calls.append((
            tuple(held), name, len(node.args),
            tuple(kw.arg for kw in node.keywords if kw.arg),
            receiver_key, receiver_name, node.lineno,
        ))

    @staticmethod
    def _flatten_targets(targets):
        """Unpack tuple/list/starred assignment targets —
        ``a, self._x = ...`` writes ``self._x`` too."""
        out = []
        stack = list(targets)
        while stack:
            tgt = stack.pop()
            if isinstance(tgt, (ast.Tuple, ast.List)):
                stack.extend(tgt.elts)
            elif isinstance(tgt, ast.Starred):
                stack.append(tgt.value)
            else:
                out.append(tgt)
        return out

    #: Callable names whose no-timeout forms can block indefinitely —
    #: recorded (with the held set) for ``blocking-call-under-lock``
    #: (analysis/wholeprogram.py evaluates the shapes).
    _BLOCKING_NAMES = frozenset({
        "join", "sleep", "wait", "get", "result", "urlopen",
        "recv", "accept", "connect", "check_output", "check_call",
        "communicate",
    })

    def _visit_call(self, node: ast.Call, held: list) -> None:
        fn = node.func
        # self.method(...) while holding locks.
        if (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "self"
        ):
            self.unit.self_calls.append(
                (tuple(held), fn.attr, node.lineno)
            )
        # Cross-object calls, for the whole-program pass: what this
        # unit invokes on OTHER objects/modules (and with which locks
        # held) is the raw material for cross-module lock-order
        # composition.
        elif isinstance(fn, ast.Attribute):
            base = fn.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                self.unit.ext_calls.append(
                    (tuple(held), "selfattr", base.attr, fn.attr,
                     node.lineno)
                )
            elif isinstance(base, ast.Name) and base.id != "self":
                self.unit.ext_calls.append(
                    (tuple(held), "name", base.id, fn.attr,
                     node.lineno)
                )
            elif isinstance(base, ast.Call):
                inner = base.func
                ref = (
                    inner.attr if isinstance(inner, ast.Attribute)
                    else inner.id if isinstance(inner, ast.Name)
                    else None
                )
                if ref:
                    self.unit.ext_calls.append(
                        (tuple(held), "callresult", ref, fn.attr,
                         node.lineno)
                    )
        elif isinstance(fn, ast.Name):
            self.unit.ext_calls.append(
                (tuple(held), "bare", fn.id, None, node.lineno)
            )
        # Recorded even with nothing held: a ``*_locked`` helper runs
        # under its CALLER's lock (the whole-program pass applies the
        # ambient-lock context when evaluating shapes).
        self._note_blocking(node, held)
        # threading.Thread(target=self.m) / Thread(target=fn)
        is_thread = (
            isinstance(fn, ast.Attribute) and fn.attr == "Thread"
        ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
        if is_thread:
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                tgt = kw.value
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and self.cls is not None
                ):
                    self.cls.thread_targets.add(tgt.attr)
                elif isinstance(tgt, ast.Lambda):
                    # Thread(target=lambda: self.serve(...)) — every
                    # self-method the lambda calls runs on the new
                    # thread.
                    for sub in ast.walk(tgt.body):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id == "self"
                            and self.cls is not None
                        ):
                            self.cls.thread_targets.add(sub.func.attr)
                elif isinstance(tgt, ast.Name) and self.cls is not None:
                    # Thread(target=local_closure): the nested unit is
                    # registered as "<enclosing>.<name>".
                    self.cls.thread_targets.add(
                        f"{self.unit.name}.<{tgt.id}>"
                    )


# -- rule evaluation ---------------------------------------------------------


def _closure_acquires(units: dict) -> dict:
    """Fixpoint of acquires over intraclass self-calls."""
    result = {name: set(u.acquires) for name, u in units.items()}
    changed = True
    while changed:
        changed = False
        for name, unit in units.items():
            for _held, callee, _line in unit.self_calls:
                extra = result.get(callee)
                if extra and not extra <= result[name]:
                    result[name] |= extra
                    changed = True
    return result


def _find_cycle(edges: dict) -> list | None:
    """→ one cycle as a node list, or None.  ``edges``: node -> {node}."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in edges}
    stack: list = []

    def visit(n) -> list | None:
        color[n] = GRAY
        stack.append(n)
        for nxt in sorted(edges.get(n, ())):
            if color.get(nxt, WHITE) == GRAY:
                return stack[stack.index(nxt):] + [nxt]
            if color.get(nxt, WHITE) == WHITE and nxt in edges:
                found = visit(nxt)
                if found:
                    return found
        stack.pop()
        color[n] = BLACK
        return None

    for node in sorted(edges):
        if color[node] == WHITE:
            found = visit(node)
            if found:
                return found
    return None


def _key_str(key) -> str:
    owner, name = key
    return (
        name if owner == "<module>" or _is_foreign(key)
        else f"{owner}.{name}"
    )


def analyze_concurrency(path: str, tree: ast.Module) -> list[Finding]:
    scan = _ModuleScan(path, tree)
    findings: list[Finding] = []

    # One order graph per module: module-level registry locks are
    # shared across classes, so edges from every unit combine.
    edges: dict = {}
    edge_sites: dict = {}

    scopes: list[tuple[_ClassInfo | None, dict]] = [
        (None, scan.module_units)
    ]
    scopes += [(cls, cls.units) for cls in scan.classes.values()]

    for cls, units in scopes:
        acq_closure = _closure_acquires(units)
        for unit in units.values():
            # Direct nesting: acquiring `key` while holding `held`.
            # Foreign keys (another object's lock) are opaque here —
            # the whole-program pass resolves and orders them.
            for key, line, held in unit.acq_sites:
                if _is_foreign(key):
                    continue
                for h in held:
                    if _is_foreign(h):
                        continue
                    if h == key:
                        if not scan.is_reentrant(key):
                            findings.append(Finding(
                                path, line, "lock-self-deadlock",
                                f"{unit.name} re-acquires non-"
                                f"reentrant lock {_key_str(key)} "
                                "already held on this path",
                            ))
                        continue
                    edges.setdefault(h, set()).add(key)
                    edge_sites.setdefault((h, key), (path, line))
            # Self-call propagation.
            for held, callee, line in unit.self_calls:
                if not held:
                    continue
                callee_locks = acq_closure.get(callee) or set()
                for key in callee_locks:
                    if _is_foreign(key):
                        continue
                    for h in held:
                        if _is_foreign(h):
                            continue
                        if h == key:
                            if not scan.is_reentrant(key):
                                findings.append(Finding(
                                    path, line, "lock-self-deadlock",
                                    f"{unit.name} holds "
                                    f"{_key_str(key)} and calls "
                                    f"self.{callee}() which "
                                    "re-acquires it",
                                ))
                            continue
                        edges.setdefault(h, set()).add(key)
                        edge_sites.setdefault((h, key), (path, line))

    cycle = _find_cycle(edges)
    if cycle:
        pairs = list(zip(cycle, cycle[1:]))
        where = edge_sites[pairs[0]]
        order = " -> ".join(_key_str(k) for k in cycle)
        findings.append(Finding(
            where[0], where[1], "lock-order",
            f"inconsistent lock acquisition order (cycle {order}); "
            "two threads entering from different methods can "
            "deadlock",
        ))

    # unlocked-shared-write per class.
    for cls in scan.classes.values():
        findings.extend(_shared_write_findings(path, cls))
    return findings


def _thread_reachable(cls: _ClassInfo) -> set[str]:
    """Unit names reachable from a thread entry point via self-calls."""
    reach = {
        name for name in cls.units
        if name in cls.thread_targets
        or name.split(".")[0] in cls.thread_targets
    }
    changed = True
    while changed:
        changed = False
        for unit in cls.units.values():
            if unit.name not in reach:
                continue
            for _held, callee, _line in unit.self_calls:
                for name in cls.units:
                    if (
                        name not in reach
                        and name.split(".")[0] == callee
                    ):
                        reach.add(name)
                        changed = True
    return reach


def _lock_context_exempt(cls: _ClassInfo) -> set[str]:
    """Private helpers whose every intraclass call site already holds
    a lock (directly, from an ``__init__``-family method where no
    concurrent alias exists yet, or from another exempt helper) — the
    caller provides the critical section.  The repo's ``*_locked``
    naming convention marks exactly these."""
    call_sites: dict[str, list] = {}
    for unit in cls.units.values():
        for held, callee, _line in unit.self_calls:
            call_sites.setdefault(callee, []).append(
                (unit.name, held)
            )
    exempt: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name in cls.units:
            base = name.split(".")[0]
            if (
                name in exempt
                or name in cls.thread_targets
                or base in cls.thread_targets
            ):
                # A thread ENTRY is invoked bare by the runtime — no
                # call site provides a lock.  (Merely being reachable
                # from a thread is fine: the locked call site still
                # guards the helper.)
                continue
            if not base.startswith("_") or base.startswith("__"):
                continue
            sites = call_sites.get(base) or call_sites.get(name)
            if not sites:
                continue
            if all(
                held
                or caller.split(".")[0] in _INIT_EXEMPT
                or caller in exempt
                for caller, held in sites
            ):
                exempt.add(name)
                changed = True
    return exempt


def _shared_write_findings(path: str, cls: _ClassInfo) -> list[Finding]:
    findings: list[Finding] = []
    locked_attrs: set[str] = set()
    for unit in cls.units.values():
        for attr, _line, held in unit.writes:
            if held:
                locked_attrs.add(attr)
    reach = _thread_reachable(cls)
    exempt = _lock_context_exempt(cls)

    def unit_writes(pred):
        for unit in cls.units.values():
            base = unit.name.split(".")[0]
            if base in _INIT_EXEMPT or unit.name in exempt:
                continue
            for attr, line, held in unit.writes:
                if not held and pred(unit, attr):
                    yield unit, attr, line

    # Variant 1: attribute locked in one method, bare in another.
    if locked_attrs:
        for unit, attr, line in unit_writes(
            lambda u, a: a in locked_attrs
        ):
            findings.append(Finding(
                path, line, "unlocked-shared-write",
                f"{cls.name}.{unit.name} writes self.{attr} without "
                "a lock, but other methods guard the same attribute "
                f"with {'/'.join(sorted(cls.locks)) or 'a lock'} — "
                "inconsistent locking on shared state",
            ))
    # Variant 2: attribute written bare from two different methods,
    # at least one running on a spawned thread — unguarded
    # cross-thread shared state, even if no lock ever covers it (the
    # worse case: nobody thought about it).
    if reach:
        writers: dict[str, set[str]] = {}
        thread_written: set[str] = set()
        for unit in cls.units.values():
            base = unit.name.split(".")[0]
            if base in _INIT_EXEMPT or unit.name in exempt:
                continue
            for attr, _line, held in unit.writes:
                if held or attr in locked_attrs:
                    continue
                writers.setdefault(attr, set()).add(base)
                if unit.name in reach:
                    thread_written.add(attr)
        racy = {
            attr for attr, who in writers.items()
            if len(who) >= 2 and attr in thread_written
        }
        for unit, attr, line in unit_writes(lambda u, a: a in racy):
            findings.append(Finding(
                path, line, "unlocked-shared-write",
                f"{cls.name}.{unit.name} writes self.{attr} with no "
                "lock while a spawned thread also writes it "
                f"(thread entries: {', '.join(sorted(cls.thread_targets))}) "
                "— unguarded cross-thread shared state",
            ))
    return findings
