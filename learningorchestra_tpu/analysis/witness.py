"""Witness-vs-static cross-check — the sanitizer gate.

The runtime lock witness (:mod:`learningorchestra_tpu.concurrency_rt`,
``LO_TPU_WITNESS=1``) records the lock-acquisition orders that ACTUALLY
happened.  This module checks each witnessed edge against the static
whole-program graph (:mod:`.wholeprogram`): an observed edge the static
model lacks means the model has a FALSE NEGATIVE — an unknown lock, an
unresolved call chain, or a misnamed ``make_lock`` — and fails the
build as ``witness-unmatched-edge``.  (The reverse — static edges never
witnessed — is expected: static analysis overapproximates.)

Self-edges (``A.x -> A.x``) are exempt: identity is type-level, so two
INSTANCES of one class nesting their same-named locks witness as a
self-edge the static model cannot express (documented limit in
wholeprogram.py).
"""

from __future__ import annotations

import json
import re
from pathlib import Path

from .findings import Finding
from .wholeprogram import GlobalLockGraph

_SITE_RE = re.compile(r"^(?P<path>.*):(?P<line>\d+)$")


def load_dump(path: str | Path) -> dict:
    """A witness snapshot JSON written via ``LO_TPU_WITNESS_DUMP``."""
    with open(path) as fh:
        return json.load(fh)


def _site(edge: dict) -> tuple:
    m = _SITE_RE.match(edge.get("site") or "")
    if m:
        return m.group("path"), int(m.group("line"))
    return "<witness>", 1


def cross_check(
    snapshot: dict, graph: GlobalLockGraph
) -> list[Finding]:
    """→ findings for witnessed edges the static model cannot
    reproduce.  ``snapshot`` is :func:`concurrency_rt.snapshot` output
    (live or :func:`load_dump`-ed)."""
    findings: list[Finding] = []
    pairs = graph.edge_pairs
    for edge in snapshot.get("edges", ()):
        a, b = edge.get("from"), edge.get("to")
        if not a or not b or a == b:
            continue
        if (a, b) in pairs:
            continue
        path, line = _site(edge)
        unknown = [n for n in (a, b) if n not in graph.names]
        if unknown:
            detail = (
                f"lock(s) {', '.join(unknown)} are not in the static "
                "model at all (unregistered construction site or "
                "misnamed make_lock)"
            )
        else:
            detail = (
                "both locks are modeled but the ordering edge is "
                "missing (unresolved call chain in the static pass)"
            )
        findings.append(Finding(
            path, line, "witness-unmatched-edge",
            f"runtime witnessed lock order {a} -> {b} "
            f"({edge.get('count', 1)}x) is absent from the static "
            f"whole-program graph — {detail}; the static model has a "
            "false negative",
        ))
    return findings
