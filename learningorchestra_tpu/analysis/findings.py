"""Finding model + suppression scanning for the lochecks suite.

Every analyzer emits :class:`Finding` records — file:line, a stable
rule id, a severity, and a human message.  Suppression is inline and
rule-scoped, pylint-style::

    self._hits += 1  # lo-check: disable=unlocked-shared-write

A comment on the finding line (or the line directly above it, for
lines too long to carry a trailing comment) silences exactly the
listed rules.  ``# lo-check: disable-file=<rule>`` anywhere in a file
silences a rule file-wide.  Suppressions are deliberate, reviewed
exceptions — the tier-1 gate counts only UNSUPPRESSED error findings.
"""

from __future__ import annotations

import dataclasses
import re

#: Severities.  ``error`` findings fail the CLI / tier-1 gate;
#: ``warn`` findings are reported (worklists, e.g. the cooperative-
#: cancellation rule) but never flip the exit code.
ERROR = "error"
WARN = "warn"

_DISABLE_RE = re.compile(
    r"#\s*lo-check:\s*disable=([A-Za-z0-9_,\- ]+)"
)
_DISABLE_FILE_RE = re.compile(
    r"#\s*lo-check:\s*disable-file=([A-Za-z0-9_,\- ]+)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    file: str
    line: int
    rule: str
    message: str
    severity: str = ERROR

    def render(self) -> str:
        return (
            f"{self.file}:{self.line}: [{self.rule}] "
            f"{self.severity}: {self.message}"
        )


class Suppressions:
    """Per-file index of ``# lo-check: disable=...`` comments."""

    def __init__(self, text: str):
        self.by_line: dict[int, set[str]] = {}
        self.file_wide: set[str] = set()
        for lineno, line in enumerate(text.splitlines(), start=1):
            m = _DISABLE_RE.search(line)
            if m:
                rules = {
                    tok.strip() for tok in m.group(1).split(",")
                    if tok.strip()
                }
                self.by_line.setdefault(lineno, set()).update(rules)
            m = _DISABLE_FILE_RE.search(line)
            if m:
                self.file_wide.update(
                    tok.strip() for tok in m.group(1).split(",")
                    if tok.strip()
                )

    def covers(self, rule: str, line: int) -> bool:
        if rule in self.file_wide or "all" in self.file_wide:
            return True
        for candidate in (line, line - 1):
            rules = self.by_line.get(candidate)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


def apply_suppressions(
    findings: list[Finding], texts: dict[str, str]
) -> tuple[list[Finding], list[Finding]]:
    """→ (kept, suppressed).  ``texts`` maps file path → source text;
    findings in files without text (e.g. a deleted artifact) are kept."""
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    cache: dict[str, Suppressions] = {}
    for f in findings:
        text = texts.get(f.file)
        if text is None:
            kept.append(f)
            continue
        sup = cache.get(f.file)
        if sup is None:
            sup = cache[f.file] = Suppressions(text)
        (suppressed if sup.covers(f.rule, f.line) else kept).append(f)
    return kept, suppressed
