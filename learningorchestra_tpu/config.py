"""Typed configuration tree.

The reference spreads configuration over three tiers — Dockerfile/compose env
vars, per-service ``constants.py`` modules, and hard-coded tuning in source
(reference: microservices/binary_executor_image/constants.py,
docker-compose.yml:20-24, builder_image/server.py:57-62).  Here there is one
typed tree covering the store backend, volume roots, API server, mesh shape
and job-engine sizing, overridable from the environment.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path

from learningorchestra_tpu.concurrency_rt import make_lock


@dataclasses.dataclass
class StoreConfig:
    """Where artifacts live."""

    # Root directory for the document store (collections + WAL files).
    root: str = "~/.learningorchestra_tpu/store"
    # Root for volume-backed binaries.  The reference keys binary paths by
    # service type onto six named Docker volumes
    # (reference: microservices/binary_executor_image/Dockerfile:10-13).
    volume_root: str = "~/.learningorchestra_tpu/volumes"
    # fsync appends on every write (durable) vs. rely on OS flush (fast).
    durable_writes: bool = False
    # Document-store engine: "auto" | "native" (C++ liblodstore) | "python".
    backend: str = "auto"
    # Persistent XLA compilation cache (first TPU compile of a model is
    # 20-40s; repeat jobs across server restarts hit the disk cache).
    # Empty string disables.
    xla_cache_dir: str = "~/.learningorchestra_tpu/xla_cache"

    def store_path(self) -> Path:
        return Path(os.path.expanduser(self.root))

    def volume_path(self) -> Path:
        return Path(os.path.expanduser(self.volume_root))


@dataclasses.dataclass
class APIConfig:
    """REST front server (single entry point, replacing the KrakenD gateway +
    9 Flask containers; reference: microservices/krakend/krakend.json)."""

    host: str = "0.0.0.0"
    port: int = 80
    # Reference gateway budget: 10s timeout, 300s cache (krakend.json tail).
    request_timeout_s: float = 10.0
    cache_ttl_s: float = 300.0
    # Concurrency caps: the reference gateway bounds work with its
    # worker pool; here every ADMITTED handler holds a semaphore slot
    # and saturation answers 503 immediately (backpressure instead of
    # unbounded per-request threads), while ``max_connections`` caps
    # raw connection threads underneath (a slow-loris trickling bodies
    # never reaches the handler cap).  <=0 disables either cap.
    max_inflight: int = 64
    max_connections: int = 256
    # GET pagination cap (reference: database_api_image/constants.py:42-44).
    page_limit_max: int = 100
    page_limit_default: int = 20
    api_prefix: str = "/api/learningOrchestra/v1"
    # Host advertised in monitoring (TensorBoard) URLs.  The reference
    # builds these from the box's EXTERNAL IP so a remote client can
    # open them (binary_executor_image/utils.py:358-361); unset means
    # bind+advertise 127.0.0.1 (local dev).  The k8s deploy sets this
    # to the service/node address.
    monitoring_external_host: str | None = None


@dataclasses.dataclass
class JobConfig:
    """Async job engine sizing."""

    max_workers: int = 8
    # Reference Ray placement-group timeout
    # (binary_executor_image/server.py:16).
    start_timeout_s: float = 120.0
    # Weighted-fair dispatch weights per job class (service type) —
    # the reference's fairscheduler pool weights (fairscheduler.xml).
    # Unlisted classes weigh 1; weights are consecutive dispatches per
    # round-robin turn, so {"train": 2} gives training twice the share
    # under contention.  Env: LO_TPU_JOB_WEIGHTS='{"train": 2}'.
    class_weights: dict = dataclasses.field(default_factory=dict)
    # Preemption-retry budget per job (a body raising ``Preempted``
    # re-executes up to this many times).  Env: LO_TPU_JOB_RETRIES.
    max_preemption_retries: int = 3
    # Retry backoff: attempt N sleeps min(max, base * 2**(N-1)) with
    # U[0.5, 1.5) jitter before re-executing — preempted jobs must not
    # re-slam a recovering device pool in lockstep.
    # Env: LO_TPU_JOB_BACKOFF_S / LO_TPU_JOB_BACKOFF_MAX_S.
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 5.0
    # Default wall-clock deadline per dispatched job run (preemption
    # retries included); past it the engine watchdog fails the job
    # and reclaims its worker and chip leases.  <= 0 disables;
    # per-submit ``deadlineS`` overrides.  Env: LO_TPU_JOB_DEADLINE_S.
    deadline_s: float = 0.0
    # Graceful-shutdown drain budget: shutdown(wait=True) waits at
    # most this long for accepted work, then flips every outstanding
    # body's cancel token (jobs/cancel.py), cancels still-queued
    # futures and abandons non-cooperating threads after a short
    # grace — a deadline-failed zombie can no longer hang shutdown.
    # <= 0 keeps the legacy unbounded drain.  Env: LO_TPU_JOB_DRAIN_S.
    shutdown_drain_s: float = 0.0
    # Crash-durable job journal (jobs/journal.py): every job state
    # transition is group-committed to the _job_journal collection's
    # WAL (enqueued on the hot path, drained in FIFO batches by the
    # journal flusher within ~one batch-write time), each boot mints
    # an engine epoch (.engine_epoch) and stale-epoch stragglers are
    # refused at commit time.  Off: legacy in-memory-only engine
    # (interrupted jobs are re-flagged failed at boot, nothing is
    # re-dispatched).  Env: LO_TPU_JOB_JOURNAL.
    journal: bool = True
    # Boot-time recovery: replay the journal and RE-DISPATCH
    # recoverable jobs (train fits resume from their newest managed
    # checkpoint via the PATCH path; queued jobs re-enqueue in order).
    # Off: recovered jobs are terminally failed `orphaned-by-restart`
    # instead (operator re-runs with a bare PATCH).
    # Env: LO_TPU_JOB_JOURNAL_RECOVER.
    journal_recover: bool = True
    # Journal compaction threshold: past this many records, boot-time
    # pruning keeps only the last record of each terminal job (full
    # history is kept for live jobs).  <= 0 disables pruning.
    # Env: LO_TPU_JOB_JOURNAL_MAX.
    journal_max_records: int = 4096


@dataclasses.dataclass
class CompileCacheConfig:
    """Process-wide compiled-program cache (train/compile_cache.py):
    jitted epoch/eval callables survive across jobs so a repeated train
    spec or a same-architecture tune sweep traces once.  Complements
    ``StoreConfig.xla_cache_dir`` (which dedups only the XLA compile,
    not Python tracing or closure rebuilds)."""

    # Entry cap; <= 0 disables the cache (every job re-traces).
    # Env: LO_TPU_COMPILE_CACHE_ENTRIES.
    max_entries: int = 64
    # Estimated-resident-bytes cap (jax exposes no exact executable
    # size; each entry charges ``entry_bytes``).
    # Env: LO_TPU_COMPILE_CACHE_BYTES.
    max_bytes: int = 2 << 30
    # Per-entry byte estimate. Env: LO_TPU_COMPILE_CACHE_ENTRY_BYTES.
    entry_bytes: int = 32 << 20


@dataclasses.dataclass
class AOTConfig:
    """Durable warm start (train/aot_store.py): hot compiled programs
    are AOT-serialized to disk next to the XLA cache and restored into
    the compile cache at boot, so a restart/deploy serves its first
    dispatches without re-tracing.  Env knobs: LO_TPU_AOT_*."""

    # Master switch — OFF by default: restored executables pin exact
    # shapes/dtypes and device signatures, so durability is an
    # explicit deployment opt-in (both deploy manifests set it).
    # Env: LO_TPU_AOT_ENABLED.
    enabled: bool = False
    # On-disk executable store (blobs + hot-set manifest).
    # Env: LO_TPU_AOT_DIR.
    dir: str = "~/.learningorchestra_tpu/aot_cache"
    # Persisted-entry cap; <= 0 disables the store.
    # Env: LO_TPU_AOT_MAX_ENTRIES.
    max_entries: int = 64
    # Persisted-bytes cap (real serialized sizes from the manifest).
    # Env: LO_TPU_AOT_MAX_BYTES.
    max_bytes: int = 1 << 30
    # Boot pre-warm: restore the manifest's hot set into the compile
    # cache on a background thread at ServiceContext boot.
    # Env: LO_TPU_AOT_PREWARM.
    prewarm: bool = True
    # Fleet: warm a fresh replica against its model's recorded hot
    # bucket set BEFORE the P2C router may pick it.
    # Env: LO_TPU_AOT_REPLICA_PREWARM.
    replica_prewarm: bool = False


@dataclasses.dataclass
class ServeConfig:
    """Resident model serving (serve/): request-coalescing batched
    inference over device-pinned params (POST /serve/<model>/predict).
    Env knobs: LO_TPU_SERVE_*."""

    # Largest coalesced dispatch (rows); also the largest shape bucket,
    # so the deployment compiles <= log2(max_batch)+1 executables per
    # model.  Env: LO_TPU_SERVE_MAX_BATCH.
    max_batch: int = 64
    # Bounded request queue (rows) per served model — beyond it,
    # submit sheds load (HTTP 429 + Retry-After).
    # Env: LO_TPU_SERVE_MAX_QUEUE.
    max_queue: int = 256
    # Flush deadline: a dispatch fires at most this many ms after the
    # OLDEST waiting request arrived — the latency bound a lone request
    # pays for coalescing.  Env: LO_TPU_SERVE_FLUSH_MS.
    flush_ms: float = 5.0
    # Registry caps: resident model count and total parameter bytes
    # (real bytes, summed over param leaves).
    # Env: LO_TPU_SERVE_MAX_MODELS / LO_TPU_SERVE_MAX_BYTES.
    max_models: int = 4
    max_bytes: int = 1 << 30
    # Retry-After seconds advertised with a 429.
    # Env: LO_TPU_SERVE_RETRY_AFTER.
    retry_after_s: float = 1.0


@dataclasses.dataclass
class DecodeConfig:
    """Streaming LM decode engine (serve/decode/): resident KV page
    pools + continuous batching + SSE token streaming behind
    ``POST /serve/<model>/generate``.  Env knobs: LO_TPU_DECODE_*."""

    # Master switch: off, /generate still answers non-stream requests
    # through the solo jitted scan; stream=true is refused (406).
    # Env: LO_TPU_DECODE_ENABLED.
    enabled: bool = True
    # Largest slot bucket per KV page pool (power-of-two growth up to
    # this): bounds concurrent in-flight sequences per (model, kv
    # bucket) AND the slot dimension of every step executable.
    # Env: LO_TPU_DECODE_MAX_SLOTS.
    max_slots: int = 8
    # Largest KV-length bucket (pages per slot); also caps prompt+
    # generation length served by the engine.  The effective cap is
    # min(model max_len, this).  Env: LO_TPU_DECODE_MAX_KV.
    max_kv: int = 2048
    # Active + pending stream cap per model — beyond it, submission
    # sheds load (HTTP 429 + Retry-After).
    # Env: LO_TPU_DECODE_MAX_STREAMS.
    max_streams: int = 64
    # Server-side ceiling on a request's maxNewTokens.
    # Env: LO_TPU_DECODE_MAX_NEW.
    max_new_tokens: int = 128
    # Idle decode workers park and free their resident KV pools after
    # this long with no streams.  Env: LO_TPU_DECODE_IDLE_S.
    idle_timeout_s: float = 60.0


@dataclasses.dataclass
class FleetConfig:
    """Fleet serving (serve/fleet/): multi-replica data plane over
    leased chips with metrics-driven autoscaling.  Env knobs:
    LO_TPU_FLEET_*.  Defaults keep the fleet OFF (max 1 replica —
    classic single-batcher serving) until a deployment raises the
    bounds globally or per model (POST /serve/<model>/replicas)."""

    # Autoscaler control loop master switch (replica sets and manual
    # scaling still work when off).  Env: LO_TPU_FLEET_ENABLED.
    enabled: bool = True
    # Deployment-wide default replica bounds per served model;
    # max > 1 puts every served model on the fleet routing path.
    # Env: LO_TPU_FLEET_MIN / LO_TPU_FLEET_MAX.
    min_replicas: int = 1
    max_replicas: int = 1
    # Autoscaler tick interval; <= 0 disables the loop thread.
    # Env: LO_TPU_FLEET_INTERVAL_S.
    interval_s: float = 2.0
    # Scale-up triggers: fleet queue depth as a fraction of total
    # queue capacity sustained for up_ticks consecutive ticks, any
    # shed (429) requests, or p99 latency above up_p99_ms (0 = off).
    # Env: LO_TPU_FLEET_UP_QUEUE_FRAC / LO_TPU_FLEET_UP_TICKS /
    # LO_TPU_FLEET_UP_P99_MS.
    up_queue_frac: float = 0.25
    up_ticks: int = 2
    up_p99_ms: float = 0.0
    # Scale-down after this many consecutive empty-queue ticks.
    # Env: LO_TPU_FLEET_DOWN_TICKS.
    down_ticks: int = 5
    # Queue-depth GROWTH-SLOPE scale-up trigger (rows/second), fitted
    # by least squares over the shared rollup series
    # (lo_serving_model_queue_depth, obs/rollup.py) — reacts to a ramp
    # before the level crosses up_queue_frac.  0 = off; needs the
    # rollup engine enabled and ticking.  Env: LO_TPU_FLEET_UP_SLOPE /
    # LO_TPU_FLEET_SLOPE_WINDOW_S.
    up_slope: float = 0.0
    slope_window_s: float = 30.0
    # Cost-aware scale-up: attributed device-time fraction (per-model
    # device seconds per wall second, obs/costs.py serving ledger)
    # above this triggers scale-up — a model saturating its chip
    # scales BEFORE queues back up.  0 = off.
    # Env: LO_TPU_FLEET_UP_DEVICE_FRAC.
    up_device_frac: float = 0.0
    # Chip-lease budget when placing a new replica; on timeout the
    # scale-up is skipped and retried next tick.
    # Env: LO_TPU_FLEET_LEASE_TIMEOUT_S.
    lease_timeout_s: float = 5.0
    # Router RNG seed (P2C is seeded-deterministic, like the fault
    # plane's schedules).
    router_seed: int = 0
    # Chips leased per replica (deployment default; override per model
    # via POST /serve/<model>/replicas devicesPerReplica).  > 1 makes
    # every replica a multi-chip SHARD GROUP: params place across its
    # devices (serve/fleet/replicaset.py) — models bigger than one
    # chip serve through the same P2C/autoscaler path.
    # Env: LO_TPU_FLEET_DEVICES_PER_REPLICA.
    devices_per_replica: int = 1


@dataclasses.dataclass
class MPMDConfig:
    """MPMD pipeline-parallel training (parallel/mpmd.py): per-stage
    compiled programs driven by a host-side 1F1B dispatcher.  Env
    knobs: LO_TPU_MPMD_*."""

    # Deployment-default pipeline schedule for PipelinedTransformer
    # when the job doesn't pass one: "" keeps the estimator default
    # (gpipe); "gpipe" | "1f1b" | "mpmd" force it fleet-wide.
    # Env: LO_TPU_MPMD_SCHEDULE.
    schedule: str = ""
    # Default microbatch count when the job doesn't pass
    # n_microbatches; 0 = auto (2 × pipeline stages).
    # Env: LO_TPU_MPMD_MICRO.
    n_micro: int = 0


@dataclasses.dataclass
class ObsConfig:
    """Unified observability layer (obs/): metrics registry +
    Prometheus exposition at GET /metrics.prom + end-to-end job trace
    spans.  Env knobs: LO_TPU_OBS_*."""

    # Master switch: off makes every metric/span primitive a no-op
    # (the bench's overhead probe measures exactly this delta).
    # Env: LO_TPU_OBS_ENABLED.
    enabled: bool = True
    # Job tracing (request-id propagation + spans persisted into the
    # execution ledger); metrics stay on when only this is off.
    # Env: LO_TPU_OBS_TRACE.
    trace: bool = True
    # Label-cardinality cap per metric: past it, new label
    # combinations collapse into one ``_overflow`` series.
    # Env: LO_TPU_OBS_MAX_SERIES.
    max_series: int = 1024
    # Span cap per job trace (an epoch-per-span 10k-epoch fit must
    # not grow the ledger record without bound).
    # Env: LO_TPU_OBS_MAX_SPANS.
    max_spans: int = 512
    # Span-ledger sampling (0.0-1.0): the fraction of jobs whose span
    # trees persist, decided deterministically per requestId (a
    # retried request samples the same way).  Sampled-out jobs keep
    # every metric; only the span tree is skipped.
    # Env: LO_TPU_OBS_TRACE_SAMPLE.
    trace_sample: float = 1.0
    # Latency histogram bucket edges, milliseconds, ascending.
    # Env: LO_TPU_OBS_BUCKETS_MS (comma-separated).
    latency_buckets_ms: tuple = (
        1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
        250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
    )


@dataclasses.dataclass
class RollupConfig:
    """Windowed time-series rollups (obs/rollup.py): a daemon that
    snapshots selected registry families on a fixed tick into bounded
    ring buffers and derives windowed views — counter rates, gauge
    min/avg/max, histogram-delta quantiles — served at
    ``GET /observability/timeseries``.  Env knobs: LO_TPU_ROLLUP_*."""

    # Master switch: off, no snapshots are taken, the timeseries
    # endpoint answers empty, and SLO evaluation (which reads rollup
    # windows) is implicitly off too.  Env: LO_TPU_ROLLUP_ENABLED.
    enabled: bool = True
    # Snapshot cadence; <= 0 disables the daemon thread (tick() stays
    # callable — tests drive the schedule deterministically).
    # Env: LO_TPU_ROLLUP_TICK_S.
    tick_s: float = 10.0
    # Ring length per series: points * tick_s is the retention window
    # (defaults: 360 x 10 s = 1 h, covering the SLO slow window).
    # Env: LO_TPU_ROLLUP_POINTS.
    points: int = 360
    # Total tracked series across families; past it, NEW series are
    # dropped (counted, surfaced) instead of growing memory unbounded.
    # Env: LO_TPU_ROLLUP_MAX_SERIES.
    max_series: int = 2048
    # Extra family names to track on top of the built-in core set
    # (HTTP counters/latency, job states, queue depths, predict
    # latency).  Env: LO_TPU_ROLLUP_FAMILIES (comma-separated).
    families: tuple = ()


@dataclasses.dataclass
class SLOConfig:
    """Declarative SLO objectives + multi-window burn-rate alerting
    over the rollup series (obs/slo.py): route availability, per-model
    predict latency, job success rate — each with an error budget, a
    pending → firing → resolved alert state machine
    (``GET /observability/alerts``), ``lo_alert_active`` /
    ``lo_slo_burn_rate`` Prometheus families, and a pluggable sink
    (structured log line always; webhook POST when ``webhook`` is
    set).  Env knobs: LO_TPU_SLO_*."""

    # Master switch for evaluation; the rollup engine keeps ticking
    # when off (timeseries remain queryable).  Env: LO_TPU_SLO_ENABLED.
    enabled: bool = True
    # Route availability objective: 1 - target is the 5xx error
    # budget over the slow window.  Env: LO_TPU_SLO_AVAILABILITY.
    availability_target: float = 0.999
    # Per-model predict latency objective: at least predict_target of
    # predicts complete under predict_p99_ms.  0 ms disables the
    # objective.  Env: LO_TPU_SLO_PREDICT_P99_MS /
    # LO_TPU_SLO_PREDICT_TARGET.
    predict_p99_ms: float = 250.0
    predict_target: float = 0.99
    # Streamed-decode time-to-first-token objective: at least
    # decode_ttft_target of streams see their first token under
    # decode_ttft_ms.  0 ms disables the objective (the default — a
    # deployment opts in when it serves LMs).
    # Env: LO_TPU_SLO_DECODE_TTFT_MS / LO_TPU_SLO_DECODE_TTFT_TARGET.
    decode_ttft_ms: float = 0.0
    decode_ttft_target: float = 0.99
    # Job success objective: finished / (finished + failed + deadline)
    # over the window.  Env: LO_TPU_SLO_JOB_SUCCESS.
    job_success_target: float = 0.99
    # Multi-window burn-rate evaluation: an alert needs the burn rate
    # over BOTH windows above ``burn_threshold`` (fast catches the
    # page-now spike, slow stops a brief blip from paging).  Scaled
    # down by tests so drills run in seconds.
    # Env: LO_TPU_SLO_FAST_S / LO_TPU_SLO_SLOW_S / LO_TPU_SLO_BURN.
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    burn_threshold: float = 14.4
    # Alert state machine dwell times: a breach is ``pending`` until
    # it holds for ``for_s``, then ``firing``; a firing alert resolves
    # after ``resolve_s`` breach-free seconds.
    # Env: LO_TPU_SLO_FOR_S / LO_TPU_SLO_RESOLVE_S.
    for_s: float = 60.0
    resolve_s: float = 300.0
    # Webhook sink URL (POSTed JSON on firing/resolved transitions).
    # Empty = webhook delivery off (the default — the structured log
    # sink still records every transition).  Env: LO_TPU_SLO_WEBHOOK.
    webhook: str = ""


@dataclasses.dataclass
class CostsConfig:
    """Cost-accounting plane (obs/costs.py): per-program FLOPs/HBM
    ledgers from XLA cost/memory analysis at compile-cache build time,
    plus sampled per-dispatch device-time attribution (per job, per
    served model, per serving bucket).  Env knobs: LO_TPU_COSTS_*."""

    # Master switch: off, builders skip analysis and the per-dispatch
    # hook is one config check.  Env: LO_TPU_COSTS_ENABLED.
    enabled: bool = True
    # Deep analysis: AOT-compile each analyzed program once at build
    # time for Compiled.memory_analysis() (HBM footprint) and the
    # serialized executable size the compile cache's byte cap charges.
    # The extra XLA compile is per cache ENTRY (amortized over every
    # job that hits it) and dedups against the persistent XLA disk
    # cache; off, analysis stops at Lowered.cost_analysis() (flops /
    # bytes, no backend compile) and the byte cap falls back to the
    # flat estimate.  Env: LO_TPU_COSTS_DEEP.
    deep: bool = True
    # Per-dispatch attribution sampling (0.0-1.0): every k-th dispatch
    # records, contributions scaled by k — deterministic and unbiased.
    # QUANTIZED to 1/round(1/sample): only 1, 1/2, 1/3, ... thin —
    # 0.7 still records every dispatch; use 0.5, 0.1, 0.01 etc.
    # Env: LO_TPU_COSTS_SAMPLE.
    sample: float = 1.0
    # Ledger bounds: distinct program fingerprints / freshest jobs.
    # Env: LO_TPU_COSTS_MAX_PROGRAMS / LO_TPU_COSTS_MAX_JOBS.
    max_programs: int = 256
    max_jobs: int = 64
    # Per-chip peak FLOP/s for model-FLOPs-utilization gauges (e.g.
    # 2.75e14 for TPU v4 bf16).  0 = unknown: MFU is omitted rather
    # than fabricated.  Env: LO_TPU_COSTS_PEAK_FLOPS.
    peak_flops: float = 0.0


@dataclasses.dataclass
class ProfilingConfig:
    """On-demand profiler capture (obs/profiling.py): jax.profiler
    behind POST /observability/profile/start|stop.  Env knobs:
    LO_TPU_PROF_*."""

    # Capture root; "" derives <volume_root>/_profiles at server
    # construction.  Env: LO_TPU_PROF_DIR.
    dir: str = ""
    # Auto-stop deadline per capture (also the cap on a request's
    # maxSeconds): a forgotten capture must not trace forever.
    # Env: LO_TPU_PROF_MAX_S.
    max_seconds: float = 60.0
    # Retained captures; older ones are deleted on the next start.
    # Env: LO_TPU_PROF_MAX_CAPTURES.
    max_captures: int = 8


@dataclasses.dataclass
class FlightConfig:
    """Always-on flight recorder (obs/flight.py): bounded per-domain
    event rings holding the last N runtime events — HTTP requests,
    decode stream steps, job dispatch decisions, compile-cache builds,
    fault triggers, lock contention — each stamped with monotonic time
    and the request id.  Env knobs: LO_TPU_FLIGHT_*."""

    # Master switch.  Disabled, every record() is one global check.
    # Env: LO_TPU_FLIGHT_ENABLED.
    enabled: bool = True
    # Ring capacity per domain; the newest events win.  Retention in
    # seconds = events / event rate, so size for the fast domains
    # (decode steps) — 512 covers ~30 s of a busy decoder.
    # Env: LO_TPU_FLIGHT_EVENTS.
    events: int = 512


@dataclasses.dataclass
class BundleConfig:
    """Debug-bundle assembler (obs/bundle.py): on an SLO alert firing,
    a watchdog stall, a retries-exhausted job failure or a manual
    POST, snapshot the flight rings + metrics + rollup tails + SLO
    state + fleet ledger + journal tail into a versioned on-disk
    bundle.  Env knobs: LO_TPU_BUNDLE_*."""

    # Master switch for trigger-driven capture (the REST list/fetch
    # surface stays probeable either way).  Env: LO_TPU_BUNDLE_ENABLED.
    enabled: bool = True
    # Bundle root; "" derives <volume_root>/_bundles at server
    # construction.  Env: LO_TPU_BUNDLE_DIR.
    dir: str = ""
    # Retained bundles; oldest pruned after each build.
    # Env: LO_TPU_BUNDLE_MAX.
    max_bundles: int = 8
    # Minimum seconds between AUTO-triggered bundles: an alert storm
    # lands one bundle, not fifty (manual POSTs bypass this).
    # Env: LO_TPU_BUNDLE_DEBOUNCE_S.
    debounce_s: float = 300.0
    # Auto-start a short jax.profiler capture with each bundle (off by
    # default: a device trace is not free at incident time).
    # Env: LO_TPU_BUNDLE_PROFILE / LO_TPU_BUNDLE_PROFILE_S.
    profile: bool = False
    profile_s: float = 2.0
    # Journal records included in the bundle's tail (newest-last).
    # Env: LO_TPU_BUNDLE_JOURNAL_TAIL.
    journal_tail: int = 200


@dataclasses.dataclass
class MeshConfig:
    """Logical device-mesh shape for distributed execution.

    Axis names are fixed framework-wide:
      - ``dp``: data parallelism (batch sharding)
      - ``fsdp``: parameter sharding within the data axis (zero-style)
      - ``tp``: tensor parallelism (feature/head sharding)
      - ``sp``: sequence/context parallelism (ring attention)
      - ``pp``: pipeline stages
      - ``ep``: expert parallelism (MoE expert sharding)
    A dimension of 0 means "auto": fill with remaining devices on dp.
    """

    dp: int = 0
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    axis_names: tuple = ("dp", "fsdp", "pp", "ep", "tp", "sp")

    def shape(self, n_devices: int) -> dict:
        fixed = self.fsdp * self.tp * self.sp * self.pp * self.ep
        dp = self.dp
        if dp == 0:
            if n_devices % max(fixed, 1) != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            dp = n_devices // max(fixed, 1)
        return {
            "dp": dp,
            "fsdp": self.fsdp,
            "pp": self.pp,
            "ep": self.ep,
            "tp": self.tp,
            "sp": self.sp,
        }


@dataclasses.dataclass
class DistributedConfig:
    """Multi-host (DCN) bootstrap — replaces Ray GCS + client
    (reference: binary_executor_image/start.sh:7, server.py:13-17)."""

    coordinator_address: str | None = None  # "host:port" of process 0
    num_processes: int = 1
    process_id: int = 0
    agent_port: int = 7077  # per-host agent control port
    # Cluster mode: when set, POST /train/horovod dispatches the fit to
    # HostAgents through the task Coordinator (parallel/coordinator.py)
    # instead of fitting in-process — the reference's RayExecutor.run
    # fan-out (binary_execution.py:237-292), SPMD-style.
    task_coordinator: str | None = None  # Coordinator HTTP "host:port"
    jax_coordinator: str | None = None  # jax.distributed rendezvous
    # Cluster fit wall-clock budget; on expiry the job is cancelled at
    # the coordinator and this side records failure.  Generous default:
    # real fine-tunes run for hours.
    job_timeout_s: float = 86400.0


@dataclasses.dataclass
class HAConfig:
    """Store failover pairing (store/ha.py — the reference's mongo
    replica set, reference: docker-compose.yml:42-90)."""

    # "host:port" of the HA partner node: the standby before promotion,
    # the old primary after.  When set, serve() refuses to start — and
    # a running primary self-demotes — if the peer answers
    # /replication/status with a HIGHER election epoch (it promoted
    # over this store during a partition).  Needs no shared disk.
    peer: str = ""
    # Seconds between fence/peer-epoch checks while serving.  Bounds
    # the dual-writable window when a primary revives during its
    # standby's promotion (no shared disk = no fence file to see).
    # <= 0 keeps the server default (APIServer.FENCE_CHECK_INTERVAL_S).
    fence_interval_s: float = 0.0
    # A fenced primary automatically rejoins as the NEW primary's
    # standby (network WAL shipping into <store>.rejoined) instead of
    # exiting — mongo's stepped-down-primary-rejoins-as-secondary,
    # restoring pair redundancy with no operator action.  Off by
    # default: rejoining re-syncs the full store over the wire.
    auto_rejoin: bool = False
    # Takeover tuning for the auto-rejoined standby.  Defaults match
    # the deployed standby role's deliberately conservative window
    # (2 s x 15 = 30 s dead): an ordinary restart of the partner —
    # process boot alone exceeds a naive threshold — must never get
    # fenced out by the rejoined node.
    rejoin_interval_s: float = 2.0
    rejoin_misses: int = 15


@dataclasses.dataclass
class FaultsConfig:
    """Fault-injection plane (faults/plane.py): seeded chaos schedules
    armed at boot from ``LO_TPU_FAULT_<POINT>=<mode>[:k=v,...]`` env
    vars (e.g. ``LO_TPU_FAULT_ENGINE_DISPATCH=preempt:rate=0.5,seed=7``)
    — the API server passes ``specs`` to ``faults.load_env`` at
    construction.  Disabled (no vars) the plane costs one dict-empty
    check per probe."""

    # point-name suffix (env spelling) -> raw spec string.
    specs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClusterConfig:
    """Scale-out control plane (jobs/cluster.py): N engine processes
    over ONE store root share dispatch through a store-backed claim
    table with heartbeat-renewed leases.  Requires the python store
    backend (the claim table needs its WAL-refresh coherence
    primitive); single-engine deployments leave it off and pay only a
    None-check per dispatch."""

    # Join the cluster at boot.  Env: LO_TPU_CLUSTER_ENABLED.
    enabled: bool = False
    # Stable engine identity in the claim table ("" derives
    # engine-<pid>).  Two engines sharing an id would see each other's
    # claims as their own — give each process a distinct one.
    # Env: LO_TPU_CLUSTER_ENGINE_ID.
    engine_id: str = ""
    # Lease renewal cadence.  Env: LO_TPU_CLUSTER_HEARTBEAT_S.
    heartbeat_s: float = 1.0
    # A claim (or engine) whose heartbeat is older than this is dead
    # and stealable.  Must comfortably exceed heartbeat_s; the two
    # engines' clocks must agree to within it.
    # Env: LO_TPU_CLUSTER_TTL_S.
    ttl_s: float = 5.0
    # Expired-claim sweep cadence.  Env: LO_TPU_CLUSTER_SWEEP_S.
    sweep_s: float = 2.0


@dataclasses.dataclass
class TenantConfig:
    """Per-tenant fair-share admission (jobs/cluster.py
    TenantAdmission): quotas on the X-Tenant request header, enforced
    at the API tier with 429 + Retry-After.  Under clustering the
    counters live in the claim collection so every engine rejects
    identically.  0 disables a quota."""

    # Max queued-but-undispatched jobs per tenant.
    # Env: LO_TPU_TENANT_MAX_QUEUED.
    max_queued: int = 0
    # Max concurrently RUNNING fits (executor/distributed classes)
    # per tenant.  Env: LO_TPU_TENANT_MAX_RUNNING.
    max_running: int = 0
    # Retry-After seconds on a quota rejection.
    # Env: LO_TPU_TENANT_RETRY_AFTER_S.
    retry_after_s: float = 1.0


@dataclasses.dataclass
class Config:
    store: StoreConfig = dataclasses.field(default_factory=StoreConfig)
    api: APIConfig = dataclasses.field(default_factory=APIConfig)
    jobs: JobConfig = dataclasses.field(default_factory=JobConfig)
    compile_cache: CompileCacheConfig = dataclasses.field(
        default_factory=CompileCacheConfig
    )
    aot: AOTConfig = dataclasses.field(default_factory=AOTConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    decode: DecodeConfig = dataclasses.field(
        default_factory=DecodeConfig
    )
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    mpmd: MPMDConfig = dataclasses.field(default_factory=MPMDConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    rollup: RollupConfig = dataclasses.field(
        default_factory=RollupConfig
    )
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
    costs: CostsConfig = dataclasses.field(default_factory=CostsConfig)
    profiling: ProfilingConfig = dataclasses.field(
        default_factory=ProfilingConfig
    )
    flight: FlightConfig = dataclasses.field(
        default_factory=FlightConfig
    )
    bundle: BundleConfig = dataclasses.field(
        default_factory=BundleConfig
    )
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    dist: DistributedConfig = dataclasses.field(
        default_factory=DistributedConfig
    )
    ha: HAConfig = dataclasses.field(default_factory=HAConfig)
    faults: FaultsConfig = dataclasses.field(
        default_factory=FaultsConfig
    )
    cluster: ClusterConfig = dataclasses.field(
        default_factory=ClusterConfig
    )
    tenant: TenantConfig = dataclasses.field(
        default_factory=TenantConfig
    )

    @staticmethod
    def from_env() -> "Config":
        """Build a config from LO_TPU_* environment variables."""
        cfg = Config()
        env = os.environ
        if "LO_TPU_STORE_ROOT" in env:
            cfg.store.root = env["LO_TPU_STORE_ROOT"]
        if "LO_TPU_VOLUME_ROOT" in env:
            cfg.store.volume_root = env["LO_TPU_VOLUME_ROOT"]
        if "LO_TPU_STORE_BACKEND" in env:
            cfg.store.backend = env["LO_TPU_STORE_BACKEND"]
        if "LO_TPU_XLA_CACHE" in env:  # "" disables
            cfg.store.xla_cache_dir = env["LO_TPU_XLA_CACHE"]
        if "LO_TPU_API_PORT" in env:
            cfg.api.port = int(env["LO_TPU_API_PORT"])
        if "LO_TPU_MONITORING_EXTERNAL_HOST" in env:
            cfg.api.monitoring_external_host = (
                env["LO_TPU_MONITORING_EXTERNAL_HOST"] or None
            )
        if "LO_TPU_MAX_WORKERS" in env:
            cfg.jobs.max_workers = int(env["LO_TPU_MAX_WORKERS"])
        if "LO_TPU_JOB_WEIGHTS" in env:
            import json as _json

            cfg.jobs.class_weights = {
                str(k): int(v)
                for k, v in _json.loads(env["LO_TPU_JOB_WEIGHTS"]).items()
            }
        if "LO_TPU_JOB_RETRIES" in env:
            cfg.jobs.max_preemption_retries = int(
                env["LO_TPU_JOB_RETRIES"]
            )
        if "LO_TPU_JOB_BACKOFF_S" in env:
            cfg.jobs.retry_backoff_s = float(env["LO_TPU_JOB_BACKOFF_S"])
        if "LO_TPU_JOB_BACKOFF_MAX_S" in env:
            cfg.jobs.retry_backoff_max_s = float(
                env["LO_TPU_JOB_BACKOFF_MAX_S"]
            )
        if "LO_TPU_JOB_DEADLINE_S" in env:
            cfg.jobs.deadline_s = float(env["LO_TPU_JOB_DEADLINE_S"])
        if "LO_TPU_JOB_DRAIN_S" in env:
            cfg.jobs.shutdown_drain_s = float(
                env["LO_TPU_JOB_DRAIN_S"]
            )
        # Fault-injection schedules: every LO_TPU_FAULT_<POINT> var is
        # carried verbatim; the API server arms them via faults.load_env
        # (bad specs are rejected LOUDLY there — a typo'd chaos knob
        # silently doing nothing would fake a green drill).
        for key, raw in env.items():
            if key.startswith("LO_TPU_FAULT_") and raw.strip():
                cfg.faults.specs[key[len("LO_TPU_FAULT_"):]] = raw
        if "LO_TPU_COMPILE_CACHE_ENTRIES" in env:
            cfg.compile_cache.max_entries = int(
                env["LO_TPU_COMPILE_CACHE_ENTRIES"]
            )
        if "LO_TPU_COMPILE_CACHE_BYTES" in env:
            cfg.compile_cache.max_bytes = int(
                env["LO_TPU_COMPILE_CACHE_BYTES"]
            )
        if "LO_TPU_COMPILE_CACHE_ENTRY_BYTES" in env:
            cfg.compile_cache.entry_bytes = int(
                env["LO_TPU_COMPILE_CACHE_ENTRY_BYTES"]
            )
        if "LO_TPU_SERVE_MAX_BATCH" in env:
            cfg.serve.max_batch = int(env["LO_TPU_SERVE_MAX_BATCH"])
        if "LO_TPU_SERVE_MAX_QUEUE" in env:
            cfg.serve.max_queue = int(env["LO_TPU_SERVE_MAX_QUEUE"])
        if "LO_TPU_SERVE_FLUSH_MS" in env:
            cfg.serve.flush_ms = float(env["LO_TPU_SERVE_FLUSH_MS"])
        if "LO_TPU_SERVE_MAX_MODELS" in env:
            cfg.serve.max_models = int(env["LO_TPU_SERVE_MAX_MODELS"])
        if "LO_TPU_SERVE_MAX_BYTES" in env:
            cfg.serve.max_bytes = int(env["LO_TPU_SERVE_MAX_BYTES"])
        if "LO_TPU_SERVE_RETRY_AFTER" in env:
            cfg.serve.retry_after_s = float(
                env["LO_TPU_SERVE_RETRY_AFTER"]
            )
        def _bool_env(key: str) -> bool:
            # Same loud-rejection contract as LO_HA_AUTO_REJOIN: a
            # silently-misparsed "true" would run production blind.
            raw = env[key].strip().lower()
            if raw in ("1", "true", "yes", "on"):
                return True
            if raw in ("0", "false", "no", "off", ""):
                return False
            raise ValueError(
                f"{key}={env[key]!r} is not a recognized boolean "
                "(use 1/0, true/false, yes/no, on/off)"
            )

        if "LO_TPU_JOB_JOURNAL" in env:
            cfg.jobs.journal = _bool_env("LO_TPU_JOB_JOURNAL")
        if "LO_TPU_JOB_JOURNAL_RECOVER" in env:
            cfg.jobs.journal_recover = _bool_env(
                "LO_TPU_JOB_JOURNAL_RECOVER"
            )
        if "LO_TPU_JOB_JOURNAL_MAX" in env:
            cfg.jobs.journal_max_records = int(
                env["LO_TPU_JOB_JOURNAL_MAX"]
            )
        if "LO_TPU_CLUSTER_ENABLED" in env:
            cfg.cluster.enabled = _bool_env("LO_TPU_CLUSTER_ENABLED")
        if "LO_TPU_CLUSTER_ENGINE_ID" in env:
            cfg.cluster.engine_id = env["LO_TPU_CLUSTER_ENGINE_ID"]
        if "LO_TPU_CLUSTER_HEARTBEAT_S" in env:
            cfg.cluster.heartbeat_s = float(
                env["LO_TPU_CLUSTER_HEARTBEAT_S"]
            )
        if "LO_TPU_CLUSTER_TTL_S" in env:
            cfg.cluster.ttl_s = float(env["LO_TPU_CLUSTER_TTL_S"])
        if "LO_TPU_CLUSTER_SWEEP_S" in env:
            cfg.cluster.sweep_s = float(env["LO_TPU_CLUSTER_SWEEP_S"])
        if "LO_TPU_TENANT_MAX_QUEUED" in env:
            cfg.tenant.max_queued = int(
                env["LO_TPU_TENANT_MAX_QUEUED"]
            )
        if "LO_TPU_TENANT_MAX_RUNNING" in env:
            cfg.tenant.max_running = int(
                env["LO_TPU_TENANT_MAX_RUNNING"]
            )
        if "LO_TPU_TENANT_RETRY_AFTER_S" in env:
            cfg.tenant.retry_after_s = float(
                env["LO_TPU_TENANT_RETRY_AFTER_S"]
            )
        if "LO_TPU_AOT_ENABLED" in env:
            cfg.aot.enabled = _bool_env("LO_TPU_AOT_ENABLED")
        if "LO_TPU_AOT_DIR" in env:
            cfg.aot.dir = env["LO_TPU_AOT_DIR"]
        if "LO_TPU_AOT_MAX_ENTRIES" in env:
            cfg.aot.max_entries = int(env["LO_TPU_AOT_MAX_ENTRIES"])
        if "LO_TPU_AOT_MAX_BYTES" in env:
            cfg.aot.max_bytes = int(env["LO_TPU_AOT_MAX_BYTES"])
        if "LO_TPU_AOT_PREWARM" in env:
            cfg.aot.prewarm = _bool_env("LO_TPU_AOT_PREWARM")
        if "LO_TPU_AOT_REPLICA_PREWARM" in env:
            cfg.aot.replica_prewarm = _bool_env(
                "LO_TPU_AOT_REPLICA_PREWARM"
            )
        if "LO_TPU_DECODE_ENABLED" in env:
            cfg.decode.enabled = _bool_env("LO_TPU_DECODE_ENABLED")
        if "LO_TPU_DECODE_MAX_SLOTS" in env:
            cfg.decode.max_slots = int(env["LO_TPU_DECODE_MAX_SLOTS"])
        if "LO_TPU_DECODE_MAX_KV" in env:
            cfg.decode.max_kv = int(env["LO_TPU_DECODE_MAX_KV"])
        if "LO_TPU_DECODE_MAX_STREAMS" in env:
            cfg.decode.max_streams = int(
                env["LO_TPU_DECODE_MAX_STREAMS"]
            )
        if "LO_TPU_DECODE_MAX_NEW" in env:
            cfg.decode.max_new_tokens = int(
                env["LO_TPU_DECODE_MAX_NEW"]
            )
        if "LO_TPU_DECODE_IDLE_S" in env:
            cfg.decode.idle_timeout_s = float(
                env["LO_TPU_DECODE_IDLE_S"]
            )
        if "LO_TPU_FLEET_ENABLED" in env:
            cfg.fleet.enabled = _bool_env("LO_TPU_FLEET_ENABLED")
        if "LO_TPU_FLEET_MIN" in env:
            cfg.fleet.min_replicas = int(env["LO_TPU_FLEET_MIN"])
        if "LO_TPU_FLEET_MAX" in env:
            cfg.fleet.max_replicas = int(env["LO_TPU_FLEET_MAX"])
        if "LO_TPU_FLEET_INTERVAL_S" in env:
            cfg.fleet.interval_s = float(env["LO_TPU_FLEET_INTERVAL_S"])
        if "LO_TPU_FLEET_UP_QUEUE_FRAC" in env:
            cfg.fleet.up_queue_frac = float(
                env["LO_TPU_FLEET_UP_QUEUE_FRAC"]
            )
        if "LO_TPU_FLEET_UP_TICKS" in env:
            cfg.fleet.up_ticks = int(env["LO_TPU_FLEET_UP_TICKS"])
        if "LO_TPU_FLEET_DOWN_TICKS" in env:
            cfg.fleet.down_ticks = int(env["LO_TPU_FLEET_DOWN_TICKS"])
        if "LO_TPU_FLEET_UP_P99_MS" in env:
            cfg.fleet.up_p99_ms = float(env["LO_TPU_FLEET_UP_P99_MS"])
        if "LO_TPU_FLEET_UP_SLOPE" in env:
            cfg.fleet.up_slope = float(env["LO_TPU_FLEET_UP_SLOPE"])
        if "LO_TPU_FLEET_SLOPE_WINDOW_S" in env:
            cfg.fleet.slope_window_s = float(
                env["LO_TPU_FLEET_SLOPE_WINDOW_S"]
            )
        if "LO_TPU_FLEET_UP_DEVICE_FRAC" in env:
            cfg.fleet.up_device_frac = float(
                env["LO_TPU_FLEET_UP_DEVICE_FRAC"]
            )
        if "LO_TPU_FLEET_LEASE_TIMEOUT_S" in env:
            cfg.fleet.lease_timeout_s = float(
                env["LO_TPU_FLEET_LEASE_TIMEOUT_S"]
            )
        if "LO_TPU_FLEET_DEVICES_PER_REPLICA" in env:
            cfg.fleet.devices_per_replica = int(
                env["LO_TPU_FLEET_DEVICES_PER_REPLICA"]
            )
        if cfg.fleet.devices_per_replica < 1:
            raise ValueError(
                "LO_TPU_FLEET_DEVICES_PER_REPLICA must be >= 1, got "
                f"{cfg.fleet.devices_per_replica}"
            )
        if "LO_TPU_MPMD_SCHEDULE" in env:
            cfg.mpmd.schedule = env["LO_TPU_MPMD_SCHEDULE"].strip()
        if cfg.mpmd.schedule not in ("", "gpipe", "1f1b", "mpmd"):
            # Loud at boot, not deep inside the first pipeline fit.
            raise ValueError(
                "LO_TPU_MPMD_SCHEDULE must be one of gpipe|1f1b|mpmd "
                f"(or empty for the estimator default), got "
                f"{cfg.mpmd.schedule!r}"
            )
        if "LO_TPU_MPMD_MICRO" in env:
            cfg.mpmd.n_micro = int(env["LO_TPU_MPMD_MICRO"])
        if not 1 <= cfg.fleet.min_replicas <= cfg.fleet.max_replicas:
            # Loud at BOOT, like the boolean knobs: deferred, these
            # bounds first fail inside a predict's lazy ReplicaSet
            # construction — an env typo becoming per-request 500s.
            raise ValueError(
                "fleet replica bounds need 1 <= LO_TPU_FLEET_MIN "
                f"({cfg.fleet.min_replicas}) <= LO_TPU_FLEET_MAX "
                f"({cfg.fleet.max_replicas})"
            )
        if "LO_TPU_OBS_ENABLED" in env:
            cfg.obs.enabled = _bool_env("LO_TPU_OBS_ENABLED")
        if "LO_TPU_OBS_TRACE" in env:
            cfg.obs.trace = _bool_env("LO_TPU_OBS_TRACE")
        if "LO_TPU_OBS_MAX_SERIES" in env:
            cfg.obs.max_series = int(env["LO_TPU_OBS_MAX_SERIES"])
        if "LO_TPU_OBS_MAX_SPANS" in env:
            cfg.obs.max_spans = int(env["LO_TPU_OBS_MAX_SPANS"])
        def _fraction_env(key: str) -> float:
            # Sampling knobs: a typo'd rate silently clamping would
            # either drop every trace or record everything — reject
            # out-of-range values LOUDLY at boot.
            value = float(env[key])
            if not 0.0 <= value <= 1.0:
                raise ValueError(
                    f"{key}={env[key]!r} must be a fraction in "
                    "[0.0, 1.0]"
                )
            return value

        if "LO_TPU_OBS_TRACE_SAMPLE" in env:
            cfg.obs.trace_sample = _fraction_env(
                "LO_TPU_OBS_TRACE_SAMPLE"
            )
        if "LO_TPU_ROLLUP_ENABLED" in env:
            cfg.rollup.enabled = _bool_env("LO_TPU_ROLLUP_ENABLED")
        if "LO_TPU_ROLLUP_TICK_S" in env:
            cfg.rollup.tick_s = float(env["LO_TPU_ROLLUP_TICK_S"])
        if "LO_TPU_ROLLUP_POINTS" in env:
            cfg.rollup.points = int(env["LO_TPU_ROLLUP_POINTS"])
        if "LO_TPU_ROLLUP_MAX_SERIES" in env:
            cfg.rollup.max_series = int(
                env["LO_TPU_ROLLUP_MAX_SERIES"]
            )
        if "LO_TPU_ROLLUP_FAMILIES" in env:
            cfg.rollup.families = tuple(
                tok.strip()
                for tok in env["LO_TPU_ROLLUP_FAMILIES"].split(",")
                if tok.strip()
            )
        if "LO_TPU_SLO_ENABLED" in env:
            cfg.slo.enabled = _bool_env("LO_TPU_SLO_ENABLED")
        if "LO_TPU_SLO_AVAILABILITY" in env:
            cfg.slo.availability_target = _fraction_env(
                "LO_TPU_SLO_AVAILABILITY"
            )
        if "LO_TPU_SLO_PREDICT_P99_MS" in env:
            cfg.slo.predict_p99_ms = float(
                env["LO_TPU_SLO_PREDICT_P99_MS"]
            )
        if "LO_TPU_SLO_PREDICT_TARGET" in env:
            cfg.slo.predict_target = _fraction_env(
                "LO_TPU_SLO_PREDICT_TARGET"
            )
        if "LO_TPU_SLO_JOB_SUCCESS" in env:
            cfg.slo.job_success_target = _fraction_env(
                "LO_TPU_SLO_JOB_SUCCESS"
            )
        if "LO_TPU_SLO_DECODE_TTFT_MS" in env:
            cfg.slo.decode_ttft_ms = float(
                env["LO_TPU_SLO_DECODE_TTFT_MS"]
            )
        if "LO_TPU_SLO_DECODE_TTFT_TARGET" in env:
            cfg.slo.decode_ttft_target = _fraction_env(
                "LO_TPU_SLO_DECODE_TTFT_TARGET"
            )
        if "LO_TPU_SLO_FAST_S" in env:
            cfg.slo.fast_window_s = float(env["LO_TPU_SLO_FAST_S"])
        if "LO_TPU_SLO_SLOW_S" in env:
            cfg.slo.slow_window_s = float(env["LO_TPU_SLO_SLOW_S"])
        if "LO_TPU_SLO_BURN" in env:
            cfg.slo.burn_threshold = float(env["LO_TPU_SLO_BURN"])
        if "LO_TPU_SLO_FOR_S" in env:
            cfg.slo.for_s = float(env["LO_TPU_SLO_FOR_S"])
        if "LO_TPU_SLO_RESOLVE_S" in env:
            cfg.slo.resolve_s = float(env["LO_TPU_SLO_RESOLVE_S"])
        if "LO_TPU_SLO_WEBHOOK" in env:
            cfg.slo.webhook = env["LO_TPU_SLO_WEBHOOK"].strip()
        # A target of 1.0 has a ZERO error budget — burn rate would
        # divide by zero on the first bad event.  Reject loudly at
        # boot, like the fleet bounds.
        for knob, value in (
            ("LO_TPU_SLO_AVAILABILITY", cfg.slo.availability_target),
            ("LO_TPU_SLO_PREDICT_TARGET", cfg.slo.predict_target),
            ("LO_TPU_SLO_JOB_SUCCESS", cfg.slo.job_success_target),
            ("LO_TPU_SLO_DECODE_TTFT_TARGET",
             cfg.slo.decode_ttft_target),
        ):
            if value >= 1.0:
                raise ValueError(
                    f"{knob}={value!r} leaves no error budget — SLO "
                    "targets must be < 1.0"
                )
        if "LO_TPU_COSTS_ENABLED" in env:
            cfg.costs.enabled = _bool_env("LO_TPU_COSTS_ENABLED")
        if "LO_TPU_COSTS_DEEP" in env:
            cfg.costs.deep = _bool_env("LO_TPU_COSTS_DEEP")
        if "LO_TPU_COSTS_SAMPLE" in env:
            cfg.costs.sample = _fraction_env("LO_TPU_COSTS_SAMPLE")
        if "LO_TPU_COSTS_MAX_PROGRAMS" in env:
            cfg.costs.max_programs = int(
                env["LO_TPU_COSTS_MAX_PROGRAMS"]
            )
        if "LO_TPU_COSTS_MAX_JOBS" in env:
            cfg.costs.max_jobs = int(env["LO_TPU_COSTS_MAX_JOBS"])
        if "LO_TPU_COSTS_PEAK_FLOPS" in env:
            cfg.costs.peak_flops = float(
                env["LO_TPU_COSTS_PEAK_FLOPS"]
            )
        if "LO_TPU_PROF_DIR" in env:
            cfg.profiling.dir = env["LO_TPU_PROF_DIR"]
        if "LO_TPU_PROF_MAX_S" in env:
            cfg.profiling.max_seconds = float(env["LO_TPU_PROF_MAX_S"])
        if "LO_TPU_PROF_MAX_CAPTURES" in env:
            cfg.profiling.max_captures = int(
                env["LO_TPU_PROF_MAX_CAPTURES"]
            )
        if "LO_TPU_FLIGHT_ENABLED" in env:
            cfg.flight.enabled = _bool_env("LO_TPU_FLIGHT_ENABLED")
        if "LO_TPU_FLIGHT_EVENTS" in env:
            cfg.flight.events = int(env["LO_TPU_FLIGHT_EVENTS"])
        if "LO_TPU_BUNDLE_ENABLED" in env:
            cfg.bundle.enabled = _bool_env("LO_TPU_BUNDLE_ENABLED")
        if "LO_TPU_BUNDLE_DIR" in env:
            cfg.bundle.dir = env["LO_TPU_BUNDLE_DIR"]
        if "LO_TPU_BUNDLE_MAX" in env:
            cfg.bundle.max_bundles = int(env["LO_TPU_BUNDLE_MAX"])
        if "LO_TPU_BUNDLE_DEBOUNCE_S" in env:
            cfg.bundle.debounce_s = float(
                env["LO_TPU_BUNDLE_DEBOUNCE_S"]
            )
        if "LO_TPU_BUNDLE_PROFILE" in env:
            cfg.bundle.profile = _bool_env("LO_TPU_BUNDLE_PROFILE")
        if "LO_TPU_BUNDLE_PROFILE_S" in env:
            cfg.bundle.profile_s = float(
                env["LO_TPU_BUNDLE_PROFILE_S"]
            )
        if "LO_TPU_BUNDLE_JOURNAL_TAIL" in env:
            cfg.bundle.journal_tail = int(
                env["LO_TPU_BUNDLE_JOURNAL_TAIL"]
            )
        if "LO_TPU_OBS_BUCKETS_MS" in env:
            edges = tuple(
                float(tok)
                for tok in env["LO_TPU_OBS_BUCKETS_MS"].split(",")
                if tok.strip()
            )
            if not edges or list(edges) != sorted(edges):
                raise ValueError(
                    "LO_TPU_OBS_BUCKETS_MS must be a non-empty "
                    "ascending comma-separated list of milliseconds"
                )
            cfg.obs.latency_buckets_ms = edges
        if "LO_TPU_TASK_COORDINATOR" in env:
            cfg.dist.task_coordinator = env["LO_TPU_TASK_COORDINATOR"]
        if "LO_TPU_JAX_COORDINATOR" in env:
            cfg.dist.jax_coordinator = env["LO_TPU_JAX_COORDINATOR"]
        if "LO_TPU_WORLD_SIZE" in env:
            cfg.dist.num_processes = int(env["LO_TPU_WORLD_SIZE"])
        if "LO_HA_PEER" in env:
            cfg.ha.peer = env["LO_HA_PEER"]
        if "LO_HA_FENCE_INTERVAL" in env:
            cfg.ha.fence_interval_s = float(env["LO_HA_FENCE_INTERVAL"])
        if "LO_HA_AUTO_REJOIN" in env:
            # Accept the usual truthy/falsy spellings and reject the
            # rest LOUDLY: "true" silently parsing as False would leave
            # a pair without the redundancy the flag was set to provide.
            raw = env["LO_HA_AUTO_REJOIN"].strip().lower()
            if raw in ("1", "true", "yes", "on"):
                cfg.ha.auto_rejoin = True
            elif raw in ("0", "false", "no", "off", ""):
                cfg.ha.auto_rejoin = False
            else:
                raise ValueError(
                    f"LO_HA_AUTO_REJOIN={env['LO_HA_AUTO_REJOIN']!r} is "
                    "not a recognized boolean (use 1/0, true/false, "
                    "yes/no, on/off)"
                )
        if "LO_HA_REJOIN_INTERVAL" in env:
            cfg.ha.rejoin_interval_s = float(
                env["LO_HA_REJOIN_INTERVAL"]
            )
        if "LO_HA_REJOIN_MISSES" in env:
            cfg.ha.rejoin_misses = int(env["LO_HA_REJOIN_MISSES"])
        return cfg


#: Knobs read straight from the environment at their use site instead
#: of through :meth:`Config.from_env` — each for a reason: the log
#: level must apply before any config is built (config errors
#: themselves need a logger), and the flash-attention interpret
#: override is re-read per call so tests can flip it mid-process.
#: They are registered HERE because config.py is the canonical knob
#: index: the static drift gate (analysis/drift.py) fails any
#: ``LO_TPU_*`` reference that this file doesn't know about.
DIRECT_ENV_KNOBS = (
    "LO_TPU_LOG_LEVEL",        # log.py: root level, default INFO
    "LO_TPU_FLASH_INTERPRET",  # ops/attention.py: "1" forces the
                               # Pallas interpreter, "0" forces
                               # compiled kernels
    # Runtime lock witness (concurrency_rt.py) — read at lock-
    # construction time, which happens while THIS module is still
    # importing (config's own singleton lock), so they cannot ride
    # Config.from_env.
    "LO_TPU_WITNESS",          # "1" instruments make_lock/make_rlock
    "LO_TPU_WITNESS_STALL_S",  # stall-watchdog threshold (default 30)
    "LO_TPU_WITNESS_DUMP",     # path: dump the witnessed-order graph
                               # JSON at exit for lo_check --witness
)

_lock = make_lock("config._lock")
_config: Config | None = None


def get_config() -> Config:
    global _config
    with _lock:
        if _config is None:
            _config = Config.from_env()
        return _config


def set_config(cfg: Config) -> None:
    global _config
    with _lock:
        _config = cfg
