"""The HTTP front server and route table.

Route scheme (reference: microservices/krakend/krakend.json):
``{verb} /api/learningOrchestra/v1/{service}/{tool}[/{name}]``, with the
dataset service's paginated GET as the universal poll path (SURVEY §3.5).
Status mapping follows the reference's validation pipeline: 409 duplicate
name, 404 missing artifact, 406 semantic errors, 201 created with the
artifact's GET URI in the body (binary_executor_image/server.py:99-107).

Implementation: stdlib ``ThreadingHTTPServer`` + a regex route registry —
no web-framework dependency; handlers are thin adapters onto the service
classes.
"""

from __future__ import annotations

import json
import re
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlparse

from learningorchestra_tpu import faults
from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.config import Config, get_config
from learningorchestra_tpu.jobs.cluster import QuotaExceeded, bind_tenant
from learningorchestra_tpu.jobs.leases import LeaseTimeout
from learningorchestra_tpu.obs import metrics as obs_metrics
from learningorchestra_tpu.obs import tracing as obs_tracing
from learningorchestra_tpu.obs.bundle import (
    BundleBusy,
    BundleError,
    BundleNotFound,
)
from learningorchestra_tpu.obs.profiling import (
    ProfilerConflict,
    ProfilerError,
    ProfilerNotFound,
)
from learningorchestra_tpu.services import (
    BuilderService,
    DatasetService,
    ExecutorService,
    ExploreService,
    FunctionService,
    ModelService,
    ServiceContext,
    TransformService,
)
from learningorchestra_tpu.services.context import (
    ConflictError,
    NotFoundError,
    ValidationError,
)
from learningorchestra_tpu.serve.batcher import QueueFull
from learningorchestra_tpu.serve.registry import ServeError
from learningorchestra_tpu.store.artifacts import DuplicateArtifact
from learningorchestra_tpu.toolkit import registry
from learningorchestra_tpu.toolkit.registry import RegistryError


class BadRequest(Exception):
    """Malformed client input (non-JSON body handled separately) → 400."""


class Router:
    """Regex route table: (verb, pattern) → handler(match, body, query).

    Per-route flags carry the gateway budget semantics (reference:
    krakend.json global ``timeout``/``cache_ttl`` + metrics exporter):
    ``cacheable`` opts a GET into the response cache (poll GETs must
    NOT cache — job completion writes through the store, not HTTP, so a
    TTL cache would serve stale ``finished`` flags); ``no_timeout``
    exempts deliberate long-polls (observe) from the request deadline.
    """

    def __init__(self, prefix: str):
        self.prefix = prefix.rstrip("/")
        self.routes: list[tuple[str, re.Pattern, Callable, str, dict]] = []

    def add(self, verb: str, pattern: str, handler: Callable, *,
            cacheable: bool = False, no_timeout: bool = False) -> None:
        full = re.compile("^" + self.prefix + pattern + "/?$")
        verb = verb.upper()
        self.routes.append((
            verb, full, handler, f"{verb} {pattern}",
            {"cacheable": cacheable, "no_timeout": no_timeout},
        ))

    def resolve(self, verb: str, path: str):
        """→ (handler, match, route_key, flags) | (None, None, key, {})."""
        matched_path = False
        for route_verb, pattern, handler, key, flags in self.routes:
            m = pattern.match(path)
            if m:
                matched_path = True
                if route_verb == verb:
                    return handler, m, key, flags
        key = "405" if matched_path else "404"
        return None, None, key, {"matched_path": matched_path}

    def dispatch(self, verb: str, path: str, body: dict, query: dict):
        handler, m, _key, flags = self.resolve(verb, path)
        if handler is None:
            if flags.get("matched_path"):
                return 405, {
                    "error": f"method {verb} not allowed on {path}"
                }
            return 404, {"error": f"no such route: {path}"}
        return handler(m, body, query)


class _BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a hard cap on connection threads.

    The ``max_inflight`` semaphore bounds ADMITTED handlers, but
    stdlib ThreadingMixIn spawns one thread per accepted connection
    before a byte of the request is parsed — a slow-loris client
    trickling request bodies would grow threads without bound
    underneath the handler cap.  Beyond ``max_connections`` the
    socket is closed immediately on accept.
    """

    daemon_threads = True

    def __init__(self, addr, handler, *, max_connections: int = 256):
        self._conn_slots = (
            threading.BoundedSemaphore(max_connections)
            if max_connections > 0 else None
        )
        super().__init__(addr, handler)

    def process_request(self, request, client_address):
        if self._conn_slots is not None and \
                not self._conn_slots.acquire(blocking=False):
            self.shutdown_request(request)
            return
        try:
            super().process_request(request, client_address)
        except BaseException:
            if self._conn_slots is not None:
                self._conn_slots.release()
            raise

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            if self._conn_slots is not None:
                self._conn_slots.release()


class _Slot:
    """One in-flight-request semaphore slot with shared ownership.

    The gateway dispatcher and (for timed-out requests) the abandoned
    handler worker each own a reference; the underlying semaphore slot
    frees only when the LAST owner releases.  This is what makes the
    ``max_inflight`` cap bound real threads: a 504'd request's zombie
    handler keeps its slot until the handler actually returns.
    """

    def __init__(self, sem):
        self._sem = sem
        self._lock = make_lock("_Slot._lock")
        self._owners = 1

    def share(self) -> None:
        with self._lock:
            self._owners += 1

    def release(self) -> None:
        if self._sem is None:
            return
        with self._lock:
            self._owners -= 1
            if self._owners > 0:
                return
        self._sem.release()


class APIServer:
    """Service wiring + route table + HTTP plumbing."""

    def __init__(self, config: Config | None = None,
                 ctx: ServiceContext | None = None):
        self.config = config or get_config()
        self.ctx = ctx or ServiceContext(self.config)
        self.dataset = DatasetService(self.ctx)
        self.transform = TransformService(self.ctx)
        self.explore = ExploreService(self.ctx)
        self.model = ModelService(self.ctx)
        self.executor = ExecutorService(self.ctx)
        self.function = FunctionService(self.ctx)
        self.builder = BuilderService(self.ctx)
        import os as _os

        from learningorchestra_tpu.services.distributed_exec import (
            DistributedExecutorService,
        )
        from learningorchestra_tpu.services.monitoring import (
            MonitoringService,
        )

        monitoring_root = _os.path.join(
            self.config.store.volume_path(), "_monitoring"
        )
        self.monitoring = MonitoringService(
            monitoring_root,
            external_host=self.config.api.monitoring_external_host,
        )
        self.distributed = DistributedExecutorService(
            self.ctx, self.monitoring
        )
        from learningorchestra_tpu.serve import ServingService

        # Resident model serving (serve/): synchronous low-latency
        # predict over device-pinned params, request-coalescing
        # micro-batches, shape-bucketed compiles.
        self.serving = ServingService(self.ctx, monitoring_root)
        # On-demand profiler capture (obs/profiling.py): jax.profiler
        # behind POST /observability/profile/start|stop — one capture
        # at a time into a bounded dir, auto-stop deadline.
        from learningorchestra_tpu.obs.profiling import ProfilerService

        prof = self.config.profiling
        self.profiler = ProfilerService(
            prof.dir or _os.path.join(
                self.config.store.volume_path(), "_profiles"
            ),
            max_seconds=prof.max_seconds,
            max_captures=prof.max_captures,
        )
        # Windowed rollups + SLO burn-rate alerting (obs/rollup.py,
        # obs/slo.py): process-wide singletons sized from THIS
        # server's config when it is the first to construct them
        # (mirroring the registry); the engine daemon snapshots
        # selected registry families each tick and the SLO service
        # evaluates its objectives on the same clock.
        from learningorchestra_tpu.obs import rollup as obs_rollup
        from learningorchestra_tpu.obs import slo as obs_slo

        self.rollup = obs_rollup.ensure_engine(self.config.rollup)
        self.slo = obs_slo.ensure_service(self.config.slo)
        self.rollup.start()
        # Always-on flight recorder + incident debug bundles
        # (obs/flight.py, obs/bundle.py): the recorder arms at boot
        # and rides every request/step at a lock-free deque append;
        # the bundle assembler snapshots rings + every subsystem's
        # live state whenever an SLO fires, a job dies terminally, a
        # lock stalls — or an operator POSTs /observability/bundle.
        from learningorchestra_tpu.obs import bundle as obs_bundle
        from learningorchestra_tpu.obs import flight as obs_flight

        obs_flight.ensure(self.config.flight)
        if not self.config.bundle.dir:
            # Derived default beside the profiler's capture store:
            # bundles are artifacts of the same volume lifecycle.
            self.config.bundle.dir = _os.path.join(
                self.config.store.volume_path(), "_bundles"
            )
        self.bundles = obs_bundle.ensure_service(
            self.config.bundle,
            providers=self._bundle_providers(),
            profiler=self.profiler,
        )
        self.slo.add_sink(self._slo_bundle_sink)
        # Unified observability (obs/): push metrics for the HTTP
        # layer, pull collectors over every subsystem's existing stats,
        # rendered at GET /metrics.prom.  The legacy JSON endpoints
        # remain as views over the same instrumentation points.
        # Handles bind lazily against the CURRENT registry (identity-
        # checked per use, like the engine/lease helpers), so a
        # reset_registry() mid-life re-homes both the push metrics and
        # the collector instead of splitting them across registries.
        self._obs_registry = None
        self._obs_rebind_lock = make_lock("APIServer._obs_rebind_lock")
        self._obs_handles()
        self.router = Router(self.config.api.api_prefix)
        self._register_routes()
        self._httpd: ThreadingHTTPServer | None = None
        # Gateway budget (reference: krakend.json global timeout /
        # cache_ttl / metrics exporter on :8090 — SURVEY §5.1, §6).
        self._cache: dict[tuple, tuple] = {}
        self._cache_lock = make_lock("APIServer._cache_lock")
        self._metrics: dict[str, dict] = {}
        self._metrics_lock = make_lock("APIServer._metrics_lock")
        n_inflight = self.config.api.max_inflight
        self._inflight = (
            threading.BoundedSemaphore(n_inflight)
            if n_inflight > 0 else None
        )
        import time as _time

        self._t_start = _time.time()
        # Shutdown/demotion coordination: the event gates the dispatch
        # path (kept-alive connections get 503+close) and ends the
        # fence watch; the lock+flag make shutdown() idempotent.
        self._shutting_down = threading.Event()
        self._shutdown_lock = make_lock("APIServer._shutdown_lock")
        self._shut_down = False
        # Idempotency ledger (mongo's retryable-writes txnNumber,
        # reference: docker-compose.yml:42-90 replica set + driver
        # retry).  Lives in the DOCUMENT STORE so records WAL-ship to
        # the standby: a mutation retried across a failover replays
        # its recorded response instead of executing twice.
        self._idem_lock = make_lock("APIServer._idem_lock")
        self._idem_writes = 0
        # Without shared storage, a primary revived DURING a standby's
        # promotion can serve until its fence watch first polls the
        # peer — the check interval bounds that dual-writable window
        # (a 2-node pair has no majority to elect with; the w:1
        # tradeoff).  Configured like every other knob (HAConfig /
        # LO_HA_FENCE_INTERVAL); floored so "0" can't hot-spin peer
        # polls.
        if self.config.ha.fence_interval_s > 0:
            self.FENCE_CHECK_INTERVAL_S = max(
                0.05, self.config.ha.fence_interval_s
            )
        # Fault-injection plane: arm any LO_TPU_FAULT_* schedules the
        # config carried, so a deployment boots straight into its
        # chaos drill.  Bad specs raise HERE (boot), loudly.
        faults.load_env({
            faults.ENV_PREFIX + suffix: spec
            for suffix, spec in self.config.faults.specs.items()
        })

    # -- debug bundles --------------------------------------------------------

    def _bundle_providers(self) -> dict:
        """Content sources for obs/bundle.py, stem → zero-arg callable.
        Each runs inside the assembler's per-provider try/except: a
        broken subsystem becomes a manifest error, not a lost bundle."""

        def metrics():
            from learningorchestra_tpu.obs.metrics import get_registry

            return get_registry().snapshot()

        def rollup():
            eng = self.rollup
            series = {}
            for fam in eng.families:
                try:
                    series[fam] = eng.timeseries(fam, max_points=60)
                except Exception as exc:  # noqa: BLE001 — one family
                    series[fam] = {"error": repr(exc)}  # at a time
            return {"status": eng.status(), "series": series}

        def slo():
            return {
                "alerts": self.slo.alerts(),
                "status": self.slo.status(),
            }

        def journal():
            tail = max(0, int(self.config.bundle.journal_tail))
            j = self.ctx.journal
            docs = self.ctx.documents
            from learningorchestra_tpu.jobs.journal import (
                JOURNAL_COLLECTION,
            )

            try:
                j.flush()
            except Exception:  # noqa: BLE001 — a flush failure still
                pass  # leaves the already-persisted records readable
            if not docs.collection_exists(JOURNAL_COLLECTION):
                return {"records": []}
            records = list(docs.find(JOURNAL_COLLECTION))
            return {"records": records[-tail:] if tail else []}

        def locks():
            from learningorchestra_tpu import concurrency_rt

            return concurrency_rt.snapshot()

        def cluster():
            doc = {
                "enabled": self.ctx.cluster is not None,
                "engines": [],
                "claims": [],
            }
            if self.ctx.cluster is not None:
                doc.update(self.ctx.cluster.status())
            if self.ctx.admission is not None:
                doc["tenants"] = self.ctx.admission.snapshot()
            return doc

        return {
            "metrics": metrics,
            "rollup": rollup,
            "slo": slo,
            "fleet": lambda: self.serving.fleet.snapshot(),
            "journal": journal,
            "faults": lambda: faults.status(),
            "locks": locks,
            "cluster": cluster,
        }

    def _slo_bundle_sink(self, event: dict) -> None:
        """SLO alert-transition sink: a ``firing`` transition IS the
        incident signal — ask for a bundle (debounced/single-flight
        inside the service; assembly runs on its own thread, so the
        rollup tick this sink rides never blocks on file IO)."""
        if event.get("state") != "firing":
            return
        self.bundles.trigger("slo_firing", {
            "slo": event.get("slo"),
            "instance": event.get("instance"),
            "burnFast": event.get("burnFast"),
            "burnSlow": event.get("burnSlow"),
        })

    # -- idempotency ----------------------------------------------------------

    #: Store collection holding idempotency records.  Underscore
    #: prefix keeps it out of the artifact namespace; it sorts first
    #: in WAL shipping, so the "begun" marker tends to reach the
    #: standby no later than the mutation's own effects.
    IDEM_COLLECTION = "_idempotency"
    #: Records older than this are swept (a retry arriving a day later
    #: is a new request, matching mongo's retryable-write session TTL).
    IDEM_TTL_S = 86400.0
    #: Sweep cadence, counted in new records.
    IDEM_SWEEP_EVERY = 512

    @staticmethod
    def _idem_id(key: str) -> int:
        """Record ``_id`` derived from the key: the store's atomic
        ``insert_unique`` then gives O(1) lock-free claim semantics
        instead of a scan under a global lock.  63-bit hash space —
        collision odds are negligible, and the stored key string is
        verified on every hit anyway."""
        import hashlib

        digest = hashlib.sha256(key.encode()).digest()
        return int.from_bytes(digest[:8], "big") >> 1

    @staticmethod
    def _idem_fingerprint(verb: str, path: str, body: dict,
                          query: dict | None = None) -> str:
        """Request identity recorded with the key: a key reused for a
        DIFFERENT mutation must be rejected, not replayed — replaying
        operation A's response to operation B would report success
        for work that never ran.  Query params are part of the
        identity: handlers receive them, so two requests differing
        only there are different operations."""
        import hashlib

        canon = json.dumps(
            [body or {}, sorted((query or {}).items())],
            sort_keys=True, default=str,
        )
        return hashlib.sha256(
            f"{verb} {path} {canon}".encode()
        ).hexdigest()[:32]

    def _idem_begin(self, key: str, fingerprint: str):
        """Claim ``key`` or report its prior outcome.

        → ``("replay", status, payload)`` — the mutation already
        completed; hand back the recorded response (exactly-once).
        → ``("mismatch", rec)`` — the key was already used for a
        DIFFERENT request (or, vanishingly, a hash collision).
        → ``("ambiguous", rec)`` — a prior attempt began but never
        recorded completion (in flight, or the primary died
        mid-handler): the system cannot know whether side effects
        happened, so the caller gets an explicit conflict instead of
        a silent double-execution.
        → ``("fresh", _id)`` — first time: a ``begun`` marker is
        durably inserted before the handler runs.
        """
        import time as _time

        from learningorchestra_tpu.store.document_store import (
            DuplicateKey,
        )

        docs = self.ctx.documents
        _id = self._idem_id(key)
        try:
            docs.insert_unique(
                self.IDEM_COLLECTION,
                {"key": key, "fp": fingerprint, "state": "begun",
                 "at": _time.time()},
                _id,
            )
        except DuplicateKey:
            rec = docs.find_one(self.IDEM_COLLECTION, _id) or {}
            if rec.get("key") != key or rec.get("fp") != fingerprint:
                return ("mismatch", rec)
            if rec.get("state") == "done":
                payload = rec.get("payload")
                return (
                    "replay",
                    rec.get("status", 200),
                    payload if payload is not None else {},
                )
            return ("ambiguous", rec)
        with self._idem_lock:
            self._idem_writes += 1
            # First keyed write after startup ALSO sweeps: the counter
            # is in-memory, so without it a server restarting before
            # SWEEP_EVERY writes would never honor the TTL and expired
            # records would accumulate across restarts (and ship to
            # every replica).
            sweep = (
                self._idem_writes == 1
                or self._idem_writes % self.IDEM_SWEEP_EVERY == 0
            )
        if sweep:
            # Off the request path: a day-sized ledger sweep must cost
            # some background thread the time, not an unlucky client.
            threading.Thread(
                target=self._idem_sweep, daemon=True
            ).start()
        return ("fresh", _id)

    def _idem_finish(self, _id: int, status: int, payload) -> None:
        """Record the terminal response for replay.  Runs in the
        handler's thread even after a gateway 504 — the REAL outcome
        is what a retry must see, not the timeout envelope."""
        if not isinstance(payload, (dict, list)):
            payload = None  # mutations return JSON; belt-and-braces
        try:
            self.ctx.documents.update_one(
                self.IDEM_COLLECTION, _id,
                {"state": "done", "status": status, "payload": payload},
            )
        except Exception:
            pass  # a lost record degrades to at-least-once, not 500

    def _idem_sweep(self) -> None:
        import time as _time

        docs = self.ctx.documents
        cutoff = _time.time() - self.IDEM_TTL_S
        if not docs.collection_exists(self.IDEM_COLLECTION):
            return
        try:
            for rec in docs.find(self.IDEM_COLLECTION):
                if rec.get("at", 0) < cutoff:
                    docs.delete_one(self.IDEM_COLLECTION, rec["_id"])
        except Exception:
            pass

    # -- helpers --------------------------------------------------------------

    def _render_status(self) -> str:
        """The ops status page body: agents, leases, jobs, fairness
        queues, and recent events, rendered server-side from state the
        process already holds — no polling scripts, a meta-refresh
        keeps it live in a browser during bring-up."""
        import html as _html
        import time as _time

        esc = _html.escape

        def table(headers, rows):
            head = "".join(f"<th>{esc(str(h))}</th>" for h in headers)
            body = "".join(
                "<tr>" + "".join(
                    f"<td>{esc(str(c))}</td>" for c in row
                ) + "</tr>"
                for row in rows
            )
            return (f"<table><thead><tr>{head}</tr></thead>"
                    f"<tbody>{body or ''}</tbody></table>")

        sections: list[str] = []

        # -- cluster agents (coordinator fetch, cluster mode only) ----
        coord = self.config.dist.task_coordinator
        if coord:
            try:
                import urllib.request as _rq

                with _rq.urlopen(
                    f"http://{coord}/agents", timeout=2
                ) as resp:
                    agents = json.loads(resp.read()).get("agents", {})
                rows = [
                    (aid, a.get("capacity", ""),
                     "yes" if a.get("alive") else "NO",
                     f"{_time.time() - a.get('last_seen', 0):.1f}s ago"
                     if a.get("last_seen") else "never")
                    for aid, a in sorted(agents.items())
                ]
                sections.append(
                    f"<h2>Agents ({len(rows)})</h2>"
                    + table(("agent", "capacity", "alive",
                             "heartbeat"), rows)
                )
            except Exception as exc:  # noqa: BLE001 — page must render
                sections.append(
                    f"<h2>Agents</h2><p class=err>coordinator "
                    f"{esc(coord)} unreachable: {esc(repr(exc))}</p>"
                )
        else:
            sections.append(
                "<h2>Agents</h2><p>in-process mode "
                "(no task coordinator configured)</p>"
            )

        # -- chip leases (snapshot never forces device discovery:
        # that could block on remote hardware) -------------------------
        snap = self.ctx.leaser.snapshot()
        free, all_devs, recent = snap["free"], snap["all"], snap["recent"]
        if snap["initialized"]:
            sections.append(
                f"<h2>Device leases</h2><p>{len(free)}/{len(all_devs)}"
                f" free — {esc(', '.join(all_devs) or 'cpu (no-op)')}"
                "</p>"
                + table(
                    ("job", "device", "held"),
                    [(label, dev, f"{t1 - t0:.2f}s")
                     for label, dev, t0, t1 in recent],
                )
            )
        else:
            sections.append(
                "<h2>Device leases</h2><p>no lease taken yet "
                "(device discovery is lazy)</p>"
            )

        # -- store HA: role, election epoch, peer (store/ha.py).  Same
        # page-must-render convention as the coordinator fetch above:
        # a bad peer or unreadable store degrades this section only.
        try:
            from learningorchestra_tpu.store.ha import (
                is_fenced,
                peer_status,
            )
            from learningorchestra_tpu.store.replica import read_epoch

            root = self.config.store.store_path()
            fence = is_fenced(root)
            # Same role logic as GET /replication/status: a fenced
            # store is not a primary, whatever this process thinks.
            role = "fenced" if fence is not None else "primary"
            ha_bits = [
                f"role: <b>{role}</b> — election epoch "
                f"{read_epoch(root)}"
            ]
            if fence is not None:
                ha_bits.append(
                    '<span class=err>FENCED by '
                    f"{esc(str(fence.get('promoted_to') or '?'))}"
                    "</span>"
                )
            peer = self.config.ha.peer
            if peer:
                st = peer_status(peer)
                if not isinstance(st, dict):
                    # A monitoring standby answers its status route
                    # (store/ha.py) — unreachable means DOWN.
                    ha_bits.append(
                        f'<span class=err>peer {esc(peer)}: '
                        "unreachable</span>"
                    )
                else:
                    ha_bits.append(
                        f"peer {esc(peer)}: "
                        f"role={esc(str(st.get('role')))} "
                        f"epoch={esc(str(st.get('epoch')))}"
                    )
            else:
                ha_bits.append("no HA peer configured")
            sections.append(
                "<h2>Store HA</h2><p>" + " · ".join(ha_bits) + "</p>"
            )
        except Exception as exc:  # noqa: BLE001 — page must render
            sections.append(
                f"<h2>Store HA</h2><p class=err>{esc(repr(exc))}</p>"
            )

        # -- jobs: running + queued per fairness class ----------------
        running = self.ctx.engine.running_jobs()
        rows = []
        for name in running[:50]:
            meta = self.ctx.artifacts.metadata.read(name) or {}
            rows.append((name, meta.get("type", ""),
                         meta.get("jobState", "")))
        depths = self.ctx.engine.queue_depths()
        sections.append(
            f"<h2>Jobs ({len(running)} live)</h2>"
            + table(("artifact", "type", "state"), rows)
            + ("<p>queued per class: " + esc(json.dumps(depths))
               + "</p>" if depths else "")
        )

        # -- recent events, failures highlighted ----------------------
        events = self.ctx.webhooks.latest_events(20)
        ev_rows = "".join(
            "<tr class={cls}><td>{ts}</td><td>{name}</td>"
            "<td>{event}</td><td>{typ}</td></tr>".format(
                cls="err" if e.get("event") == "failed" else "ok",
                ts=_time.strftime(
                    "%H:%M:%S", _time.localtime(e.get("ts", 0))
                ),
                name=esc(str(e.get("artifact", ""))),
                event=esc(str(e.get("event", ""))),
                typ=esc(str(e.get("artifactType") or "")),
            )
            for e in reversed(events)
        )
        sections.append(
            "<h2>Recent events</h2><table><thead><tr><th>time</th>"
            "<th>artifact</th><th>event</th><th>type</th></tr></thead>"
            f"<tbody>{ev_rows}</tbody></table>"
        )

        uptime = _time.time() - self._t_start
        return (
            "<!doctype html><html><head>"
            "<title>learningorchestra_tpu status</title>"
            '<meta http-equiv="refresh" content="5">'
            "<style>"
            "body{font-family:system-ui,sans-serif;margin:2em;"
            "color:#222}"
            "table{border-collapse:collapse;margin:0.5em 0}"
            "td,th{border:1px solid #ccc;padding:4px 10px;"
            "text-align:left;font-size:14px}"
            "th{background:#f0f0f0}"
            "tr.err td{background:#fde8e8}"
            ".err{color:#b00}"
            "h2{margin-top:1.2em;font-size:16px}"
            "</style></head><body>"
            "<h1>learningorchestra_tpu</h1>"
            f"<p>uptime {uptime:.0f}s — store backend "
            f"{type(self.ctx.documents).__name__} — "
            f"{len(running)} live jobs</p>"
            + "".join(sections)
            + "</body></html>"
        )

    def _uri(self, service_path: str, name: str) -> str:
        return f"{self.config.api.api_prefix}/{service_path}/{name}"

    def _created(self, service_path: str, meta: dict):
        """201 + GET URI (reference: server.py:99-107)."""
        return 201, {
            "result": self._uri(service_path, meta["name"]),
            "name": meta["name"],
            "metadata": meta,
        }

    @staticmethod
    def _page_args(query: dict):
        q = query.get("query")
        parsed = json.loads(q) if q else None
        return {
            "query": parsed,
            "skip": _int_param(query, "skip", 0),
            "limit": _int_param(query, "limit", 20),
        }

    # URL tool → stored artifact-type prefix, where they differ: the
    # reference's gateway maps /train/horovod onto type=train/tensorflow
    # and /builder/{tensorflow,pytorch} onto type=builder/horovod
    # (krakend.json backend query params), so collection GETs must list
    # the type the POST actually stored.
    _TYPE_ALIASES = {
        ("train", "horovod"): "train/tensorflow",
        ("train", "distributed"): "train/tensorflow",
        ("builder", "tensorflow"): "builder/horovod",
        ("builder", "pytorch"): "builder/horovod",
    }

    def _list_handler(self, service: str, tool: str | None = None):
        """Collection-GET handler: list a family's metadata docs.

        ``tool=None`` reads the tool from the matched URL."""

        def handler(m, b, q):
            t = tool if tool is not None else m.group("tool")
            prefix = self._TYPE_ALIASES.get(
                (service, t), f"{service}/{t}" if t else f"{service}/"
            )
            docs = self.dataset.list_metadata(prefix)
            # Internal coordinator artifacts (builder runs) are not
            # client-facing.
            return 200, [d for d in docs if not d.get("hidden")]

        return handler

    # -- route table (SURVEY §2.2) -------------------------------------------

    def _register_routes(self) -> None:
        add = self.router.add
        TOOL = r"(?P<tool>[A-Za-z0-9_\-]+)"
        NAME = r"(?P<name>[A-Za-z0-9_.\-]+)"

        # ---- Dataset ----
        def dataset_create(m, body, query):
            kind = m.group("tool")
            name, url = body.get("datasetName") or body.get("name"), \
                body.get("url")
            if not url:
                raise ValidationError("missing 'url'")
            if kind == "csv":
                shard_rows = body.get("shardRows")
                if shard_rows is not None:
                    try:
                        shard_rows = int(shard_rows)
                    except (TypeError, ValueError):
                        raise ValidationError(
                            "'shardRows' must be a positive integer"
                        ) from None
                    if shard_rows <= 0:
                        raise ValidationError(
                            "'shardRows' must be a positive integer"
                        )
                meta = self.dataset.create_csv(
                    name, url, shard_rows=shard_rows
                )
            elif kind == "tensor":
                labels_url = body.get("labelsUrl")
                if not labels_url:
                    raise ValidationError(
                        "tensor ingest needs 'labelsUrl' (.npy labels)"
                    )
                shard_rows = body.get("shardRows", 4096)
                try:
                    shard_rows = int(shard_rows)
                except (TypeError, ValueError):
                    raise ValidationError(
                        "'shardRows' must be a positive integer"
                    ) from None
                if shard_rows <= 0:
                    # Same contract as the CSV path: an explicit bad
                    # value errors, never silently takes the default.
                    raise ValidationError(
                        "'shardRows' must be a positive integer"
                    )
                try:
                    meta = self.dataset.create_tensor(
                        name, url, labels_url=labels_url,
                        shard_rows=shard_rows,
                    )
                except ValueError as exc:
                    raise ValidationError(str(exc)) from None
            else:
                meta = self.dataset.create_generic(name, url)
            return self._created(f"dataset/{kind}", meta)

        add("POST", rf"/dataset/{TOOL}", dataset_create)
        add("GET", rf"/dataset/{TOOL}", self._list_handler("dataset"))
        add(
            "GET", rf"/dataset/{TOOL}/{NAME}",
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )
        add(
            "DELETE", rf"/dataset/{TOOL}/{NAME}",
            lambda m, b, q: (
                self.dataset.delete(m.group("name")),
                (200, {"result": "deleted"}),
            )[1],
        )

        # ---- Transform: projection ----
        def projection_create(m, body, query):
            meta = self.transform.create_projection(
                body.get("projectionName") or body.get("name"),
                body.get("datasetName") or body.get("parentName"),
                body.get("fields") or [],
            )
            return self._created("transform/projection", meta)

        def projection_update(m, body, query):
            meta = self.transform.update_projection(
                body.get("projectionName") or body.get("name"),
                fields=body.get("fields"),
            )
            return 200, {"metadata": meta}

        add("POST", r"/transform/projection", projection_create)
        # Reference: PATCH /transform/projection carries the name in the
        # body (krakend.json transform block); also accept /{name}.
        add("PATCH", r"/transform/projection", projection_update)
        add(
            "PATCH", r"/transform/projection/" + NAME,
            lambda m, b, q: (
                200,
                {
                    "metadata": self.transform.update_projection(
                        m.group("name"), fields=b.get("fields")
                    )
                },
            ),
        )
        add(
            "GET", r"/transform/projection/" + NAME,
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )
        add(
            "DELETE", r"/transform/projection/" + NAME,
            lambda m, b, q: (
                self.dataset.delete(m.group("name")),
                (200, {"result": "deleted"}),
            )[1],
        )

        # ---- Transform: text (BPE tokenization) ----
        # Beyond the reference's surface (its text configs assume
        # user-shipped preprocessing in compile_code); follows the
        # projection family's verb/URI/polling contract.
        def text_create(m, body, query):
            meta = self.transform.create_text(
                body.get("name"),
                body.get("datasetName") or body.get("parentName"),
                text_field=body.get("textField"),
                label_field=body.get("labelField"),
                vocab_size=body.get("vocabSize", 8000),
                max_len=body.get("maxLen", 128),
                lowercase=body.get("lowercase", True),
                tokenizer_from=body.get("tokenizerFrom"),
                shard_rows=body.get("shardRows", 4096),
            )
            return self._created("transform/text", meta)

        add("POST", r"/transform/text", text_create)
        add("PATCH", r"/transform/text", lambda m, b, q: (
            200, {"metadata": self.transform.update_text(b.get("name"))},
        ))
        add("PATCH", r"/transform/text/" + NAME, lambda m, b, q: (
            200, {"metadata": self.transform.update_text(m.group("name"))},
        ))
        add("GET", r"/transform/text/" + NAME, lambda m, b, q: (
            200,
            self.dataset.read_page(m.group("name"), **self._page_args(q)),
        ))
        add("DELETE", r"/transform/text/" + NAME, lambda m, b, q: (
            self.dataset.delete(m.group("name")),
            (200, {"result": "deleted"}),
        )[1])

        # ---- Transform: dataType ----
        def datatype_patch(m, body, query):
            meta = self.transform.update_field_types(
                body.get("datasetName") or body.get("name"),
                body.get("types") or body.get("fields") or {},
            )
            return 200, {"metadata": meta}

        add("PATCH", r"/transform/dataType", datatype_patch)
        # Reference routes the dataType collection GET onto the dataset
        # service (krakend.json transform block → databaseapi /files);
        # per-name GET/DELETE resolve via the generic /transform/{t}
        # routes below.  _list_handler("dataset", "") lists the whole
        # dataset family (prefix "dataset").
        add("GET", r"/transform/dataType",
            self._list_handler("dataset", ""))

        # ---- Transform: generic (scikitlearn | tensorflow) ----
        def transform_create(m, body, query):
            tool = m.group("tool")
            meta = self.transform.create_generic(
                body.get("name"),
                module_path=body.get("modulePath"),
                class_name=body.get("class"),
                class_parameters=body.get("classParameters"),
                method=body.get("method"),
                method_parameters=body.get("methodParameters"),
                artifact_type=f"transform/{tool}",
                description=body.get("description", ""),
            )
            return self._created(f"transform/{tool}", meta)

        def transform_update(m, body, query):
            meta = self.transform.update_generic(
                m.group("name"),
                class_parameters=body.get("classParameters"),
                method_parameters=body.get("methodParameters"),
                description=body.get("description", ""),
            )
            return 200, {"metadata": meta}

        add("POST", rf"/transform/{TOOL}", transform_create)
        add("GET", rf"/transform/{TOOL}", self._list_handler("transform"))
        add("PATCH", rf"/transform/{TOOL}/{NAME}", transform_update)
        add(
            "GET", rf"/transform/{TOOL}/{NAME}",
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )
        add(
            "DELETE", rf"/transform/{TOOL}/{NAME}",
            lambda m, b, q: (
                self.executor.delete(m.group("name")),
                (200, {"result": "deleted"}),
            )[1],
        )

        # ---- Explore ----
        def histogram_create(m, body, query):
            meta = self.explore.create_histogram(
                body.get("histogramName") or body.get("name"),
                body.get("datasetName") or body.get("parentName"),
                body.get("fields") or [],
            )
            return self._created("explore/histogram", meta)

        add("POST", r"/explore/histogram", histogram_create)
        add(
            "GET", r"/explore/histogram/" + NAME,
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )

        def curves_create(m, body, query):
            meta = self.explore.create_curves(
                body.get("name"),
                body.get("parentName"),
                fields=body.get("fields"),
            )
            return self._created("explore/curves", meta)

        # Specific before the generic /explore/{TOOL} routes — the
        # dispatcher is first-match; GET image/metadata/list fall
        # through to the shared TOOL handlers below.
        add("POST", r"/explore/curves", curves_create)
        add(
            "PATCH", r"/explore/curves/" + NAME,
            lambda m, b, q: (
                200,
                {"metadata": self.explore.update_curves(
                    m.group("name"), fields=(b or {}).get("fields"),
                )},
            ),
        )

        def explore_create(m, body, query):
            tool = m.group("tool")
            meta = self.explore.create_plot(
                body.get("name"),
                module_path=body.get("modulePath"),
                class_name=body.get("class"),
                class_parameters=body.get("classParameters"),
                method=body.get("method", "fit_transform"),
                method_parameters=body.get("methodParameters"),
                artifact_type=f"explore/{tool}",
                color_by=body.get("colorBy"),
                description=body.get("description", ""),
            )
            return self._created(f"explore/{tool}", meta)

        def explore_update(m, body, query):
            meta = self.explore.update_plot(
                m.group("name"),
                class_parameters=body.get("classParameters"),
                method_parameters=body.get("methodParameters"),
                color_by=body.get("colorBy"),
                description=body.get("description", ""),
            )
            return 200, {"metadata": meta}

        add("POST", rf"/explore/{TOOL}", explore_create)
        add("GET", rf"/explore/{TOOL}", self._list_handler("explore"))
        add("PATCH", rf"/explore/{TOOL}/{NAME}", explore_update)
        # GET {name} returns the PNG; {name}/metadata returns docs
        # (reference: krakend.json explore block, SURVEY §2.2).
        add(
            "GET", rf"/explore/{TOOL}/{NAME}/metadata",
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )

        def explore_image(m, body, query):
            data = self.explore.read_image(m.group("name"))
            return 200, ("image/png", data)

        # NOT cacheable: a PATCH re-render writes the new PNG from a
        # background job AFTER the invalidation fires, so a TTL cache
        # could re-trap the old image for cache_ttl_s.
        add("GET", rf"/explore/{TOOL}/{NAME}", explore_image)
        add(
            "DELETE", rf"/explore/{TOOL}/{NAME}",
            lambda m, b, q: (
                self.executor.delete(m.group("name")),
                (200, {"result": "deleted"}),
            )[1],
        )

        # ---- Model ----
        def model_create(m, body, query):
            tool = m.group("tool")
            meta = self.model.create(
                body.get("modelName") or body.get("name"),
                module_path=body.get("modulePath"),
                class_name=body.get("class"),
                class_parameters=body.get("classParameters"),
                artifact_type=f"model/{tool}",
                description=body.get("description", ""),
            )
            return self._created(f"model/{tool}", meta)

        def model_update(m, body, query):
            meta = self.model.update(
                m.group("name"),
                class_parameters=body.get("classParameters"),
                description=body.get("description", ""),
            )
            return 200, {"metadata": meta}

        add("POST", rf"/model/{TOOL}", model_create)
        add("GET", rf"/model/{TOOL}", self._list_handler("model"))
        add("PATCH", rf"/model/{TOOL}/{NAME}", model_update)
        add(
            "GET", rf"/model/{TOOL}/{NAME}",
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )
        add(
            "DELETE", rf"/model/{TOOL}/{NAME}",
            lambda m, b, q: (
                self.model.delete(m.group("name")),
                (200, {"result": "deleted"}),
            )[1],
        )

        # ---- Tune / Train / Evaluate / Predict ----
        def _deadline_s(body):
            """Per-submit job deadline override (``deadlineS``): None
            inherits the engine default (LO_TPU_JOB_DEADLINE_S), 0
            disables for this job."""
            raw = body.get("deadlineS")
            if raw is None:
                return None
            try:
                return float(raw)
            except (TypeError, ValueError):
                raise ValidationError(
                    f"deadlineS must be a number, got {raw!r}"
                ) from None

        def exec_create(service):
            def handler(m, body, query):
                tool = m.group("tool")
                name = body.get("name")
                parent = body.get("parentName") or body.get("modelName")
                if service == "tune" and body.get("paramGrid"):
                    meta = self.executor.create_tune(
                        name,
                        parent_name=parent,
                        method=body.get("method", "fit"),
                        param_grid=body.get("paramGrid"),
                        method_parameters=body.get("methodParameters"),
                        scoring_parameters=body.get("scoringParameters"),
                        artifact_type=f"tune/{tool}",
                        description=body.get("description", ""),
                        deadline_s=_deadline_s(body),
                    )
                else:
                    meta = self.executor.create(
                        name,
                        parent_name=parent,
                        method=body.get("method"),
                        method_parameters=body.get("methodParameters"),
                        artifact_type=f"{service}/{tool}",
                        description=body.get("description", ""),
                        deadline_s=_deadline_s(body),
                    )
                return self._created(f"{service}/{tool}", meta)

            return handler

        def exec_update(m, body, query):
            meta = self.executor.update(
                m.group("name"),
                method_parameters=body.get("methodParameters"),
                description=body.get("description", ""),
                deadline_s=_deadline_s(body),
            )
            return 200, {"metadata": meta}

        # ---- Distributed training (reference: POST /train/horovod →
        # /distributedTraining?type=train/tensorflow, SURVEY §2.2) ----
        def distributed_train_create(m, body, query):
            meta, extra = self.distributed.create_train(
                body.get("name"),
                parent_name=body.get("parentName")
                or body.get("modelName"),
                training_parameters=body.get("trainingParameters")
                or body.get("methodParameters"),
                compile_spec=body.get("compile"),
                mesh=body.get("mesh"),
                monitoring_path=body.get("monitoringPath"),
                description=body.get("description", ""),
            )
            status, payload = self._created("train/horovod", meta)
            if extra:
                payload["extra_results"] = extra
            return status, payload

        add("POST", r"/train/(?:horovod|distributed)",
            distributed_train_create)

        def distributed_train_update(m, body, query):
            meta = self.distributed.update_train(
                m.group("name"),
                training_parameters=body.get("trainingParameters")
                or body.get("methodParameters"),
                compile_spec=body.get("compile"),
                mesh=body.get("mesh"),
                description=body.get("description", ""),
            )
            return 200, {"metadata": meta}

        add("PATCH", rf"/train/(?:horovod|distributed)/{NAME}",
            distributed_train_update)

        # ---- Monitoring (reference: GET /monitoring/tensorflow/{name} →
        # TensorBoard URL lookup, server.py:185-200) ----
        def monitoring_lookup(m, body, query):
            from learningorchestra_tpu.services.monitoring import (
                MonitoringError,
            )

            # Reserved nickname: the compiled-program cache's counter
            # endpoint (train/compile_cache.py) — hit/miss/eviction/
            # trace-time, process-wide.
            if m.group("name") in ("compileCache", "compile_cache"):
                return 200, self.monitoring.compile_cache_stats()
            # Reserved nickname: serving observability (serve/) —
            # latency percentiles, queue depth, batch occupancy,
            # bucket histogram; each poll also appends one step of
            # serving_* tfevents scalars to the serving logdir.
            if m.group("name") == "serving":
                stats = self.serving.stats()
                scalars = self.serving.snapshot_scalars(stats)
                return 200, {**stats, "scalars": scalars}
            try:
                return 200, self.monitoring.lookup(m.group("name"))
            except MonitoringError as exc:
                return 404, {"error": str(exc)}

        add("GET", rf"/monitoring/{TOOL}/{NAME}", monitoring_lookup)
        add(
            "GET", rf"/monitoring/{TOOL}",
            lambda m, b, q: (200, self.monitoring.list_sessions()),
        )
        add(
            "DELETE", rf"/monitoring/{TOOL}/{NAME}",
            lambda m, b, q: (
                200, {"stopped": self.monitoring.stop(m.group("name"))},
            ),
        )

        # ---- Serve (resident model serving, serve/) ----
        # The ONE synchronous data-plane surface: unlike every
        # executor route (async job + poll), predict answers in the
        # request — coalesced with concurrent requests into a padded
        # shape bucket, run against device-resident params.
        def serve_predict(m, body, query):
            instances = body.get("instances")
            if instances is None:
                instances = body.get("x")
            if instances is None:
                raise ValidationError("missing 'instances'")
            try:
                return 200, self.serving.predict(
                    m.group("name"), instances
                )
            except QueueFull as exc:
                # Backpressure: bounded queue full — shed load with an
                # explicit retry budget (the Retry-After header is
                # attached by the HTTP layer from 'retryAfter').
                return 429, {
                    "error": str(exc),
                    "retryAfter": self.config.serve.retry_after_s,
                }

        add("POST", rf"/serve/{NAME}/predict", serve_predict)

        def serve_generate(m, body, query):
            """Autoregressive decode against a resident LM.  With
            ``stream=true`` the return value is the DecodeStream
            itself — the HTTP layer recognizes its ``sse_events``
            surface and writes a ``text/event-stream`` body, one
            event per generated token (registered ``no_timeout``: the
            stream outlives any slot budget; backpressure lives in
            the engine's own stream cap)."""
            body = body or {}
            prompts = body.get("prompts")
            if prompts is None:
                prompts = body.get("instances")
            if prompts is None:
                raise ValidationError("missing 'prompts'")
            stream = bool(body.get("stream"))
            kwargs = {
                "max_new_tokens": int(body.get("maxNewTokens", 32)),
                "stream": stream,
                "seed": int(body.get("seed", 0)),
            }
            if body.get("temperature") is not None:
                kwargs["temperature"] = float(body["temperature"])
            if body.get("topK") is not None:
                kwargs["top_k"] = int(body["topK"])
            if body.get("topP") is not None:
                kwargs["top_p"] = float(body["topP"])
            try:
                result = self.serving.generate(
                    m.group("name"), prompts, **kwargs
                )
            except QueueFull as exc:
                return 429, {
                    "error": str(exc),
                    "retryAfter": self.config.serve.retry_after_s,
                }
            # stream=true returns the DecodeStream itself; _send
            # duck-types its sse_events surface into an SSE body.
            return 200, result

        add("POST", rf"/serve/{NAME}/generate", serve_generate,
            no_timeout=True)

        def serve_generate_abort(m, body, query):
            """Server-side abort of an in-flight decode stream: frees
            the KV page slot at the next step boundary even when the
            SSE socket is still nominally open (lost client)."""
            ok = self.serving.decode.abort(
                m.group("name"), m.group("stream"),
                reason="aborted by DELETE",
            )
            if not ok:
                return 404, {
                    "error": f"no active stream {m.group('stream')!r} "
                    f"for model {m.group('name')!r}"
                }
            return 200, {"aborted": m.group("stream")}

        add("DELETE",
            rf"/serve/{NAME}/generate/(?P<stream>[A-Za-z0-9]+)",
            serve_generate_abort)
        add(
            "POST", rf"/serve/{NAME}/load",
            lambda m, b, q: (
                200, {"result": self.serving.load(m.group("name"))},
            ),
        )

        def serve_unload(m, body, query):
            if not self.serving.unload(m.group("name")):
                return 404, {
                    "error": f"model {m.group('name')!r} is not loaded"
                }
            return 200, {"result": "unloaded"}

        add("POST", rf"/serve/{NAME}/unload", serve_unload)
        add("DELETE", rf"/serve/{NAME}", serve_unload)
        add(
            "GET", r"/serve",
            lambda m, b, q: (
                200,
                {"models": self.serving.list_loaded(),
                 "stats": self.serving.stats()},
            ),
        )

        # ---- Fleet (multi-replica data plane, serve/fleet/) ----
        # Registered BEFORE the per-model replica routes so the
        # literal "fleet" path never parses as a model name.
        add(
            "GET", r"/serve/fleet",
            lambda m, b, q: (200, self.serving.fleet.snapshot()),
        )

        def serve_replicas_get(m, body, query):
            status = self.serving.fleet.status_for(m.group("name"))
            if not status:
                return 404, {
                    "error": f"model {m.group('name')!r} has no "
                    "replica set (POST bounds/count to create one)"
                }
            return 200, status

        add("GET", rf"/serve/{NAME}/replicas", serve_replicas_get)

        def serve_replicas_post(m, body, query):
            """Create/resize a model's replica set: any of ``min``,
            ``max`` (autoscaler bounds), ``count`` (manual scale,
            clamped to the bounds) and ``devicesPerReplica`` (chips
            each replica leases; > 1 shards the params across the
            slice).  Leases chips per replica; an exhausted pool
            surfaces as the LeaseTimeout 503."""
            body = body or {}

            def _int(key):
                val = body.get(key)
                if val is None:
                    return None
                try:
                    return int(val)
                except (TypeError, ValueError):
                    raise ValidationError(
                        f"{key!r} must be an integer, got {val!r}"
                    ) from None

            mn, mx, count = _int("min"), _int("max"), _int("count")
            dpr = _int("devicesPerReplica")
            if mn is None and mx is None and count is None and (
                    dpr is None):
                raise ValidationError(
                    "body needs at least one of 'min', 'max', "
                    "'count', 'devicesPerReplica'"
                )
            return 200, self.serving.fleet.configure(
                m.group("name"), min_replicas=mn, max_replicas=mx,
                count=count, devices_per_replica=dpr,
            )

        add("POST", rf"/serve/{NAME}/replicas", serve_replicas_post)

        def serve_replicas_delete(m, body, query):
            """Dissolve the model's fleet: drain replicas, release
            chips, return to single-path serving (the model stays
            loaded).  Idempotent."""
            name = m.group("name")
            return 200, {
                "model": name,
                "dissolved": self.serving.fleet.dissolve(name),
            }

        add("DELETE", rf"/serve/{NAME}/replicas", serve_replicas_delete)

        for service in ("tune", "train", "evaluate", "predict"):
            add("POST", rf"/{service}/{TOOL}", exec_create(service))
            add(
                "GET", rf"/{service}/{TOOL}",
                self._list_handler(service),
            )
            add("PATCH", rf"/{service}/{TOOL}/{NAME}", exec_update)
            add(
                "GET", rf"/{service}/{TOOL}/{NAME}",
                lambda m, b, q: (
                    200,
                    self.dataset.read_page(
                        m.group("name"), **self._page_args(q)
                    ),
                ),
            )
            add(
                "DELETE", rf"/{service}/{TOOL}/{NAME}",
                lambda m, b, q: (
                    self.executor.delete(m.group("name")),
                    (200, {"result": "deleted"}),
                )[1],
            )

        # ---- Builder ----
        def builder_create(m, body, query):
            tool = m.group("tool")
            if tool in ("tensorflow", "pytorch", "horovod"):
                # Distributed builder: one user function on every rank
                # (reference: POST /builder/tensorflow|pytorch →
                # /builderHorovod?type=builder/horovod, SURVEY §2.2).
                n_workers = body.get("nWorkers")
                if n_workers is None:  # explicit: 0 must reach validation
                    n_workers = body.get("n_workers")
                meta = self.distributed.create_builder(
                    body.get("name"),
                    function=body.get("function")
                    or body.get("modelingCode"),
                    function_parameters=body.get("functionParameters"),
                    n_workers=n_workers,
                    description=body.get("description", ""),
                )
                return self._created(f"builder/{tool}", meta)
            metas = self.builder.create(
                training_dataset=body.get("trainDatasetName"),
                test_dataset=body.get("testDatasetName"),
                classifiers=body.get("classifiersList")
                or body.get("classifiers") or [],
                label_field=body.get("labelField", "label"),
                feature_fields=body.get("featureFields"),
                modeling_code=body.get("modelingCode"),
                classifier_parameters=body.get("classifierParameters"),
                description=body.get("description", ""),
            )
            return 201, {
                "result": [
                    self._uri("builder/sparkml", mm["name"]) for mm in metas
                ]
            }

        add("POST", rf"/builder/{TOOL}", builder_create)
        add("GET", rf"/builder/{TOOL}", self._list_handler("builder"))
        add(
            "GET", rf"/builder/{TOOL}/{NAME}",
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )
        add(
            "DELETE", rf"/builder/{TOOL}/{NAME}",
            lambda m, b, q: (
                self.executor.delete(m.group("name")),
                (200, {"result": "deleted"}),
            )[1],
        )

        # ---- Function ----
        def function_create(m, body, query):
            meta = self.function.create(
                body.get("name"),
                function=body.get("function"),
                function_parameters=body.get("functionParameters"),
                description=body.get("description", ""),
                deadline_s=_deadline_s(body),
            )
            return self._created("function/python", meta)

        def function_update(m, body, query):
            meta = self.function.update(
                m.group("name"),
                function=body.get("function"),
                function_parameters=body.get("functionParameters"),
                description=body.get("description", ""),
                deadline_s=_deadline_s(body),
            )
            return 200, {"metadata": meta}

        add("POST", r"/function/python", function_create)
        add("GET", r"/function/python",
            self._list_handler("function", "python"))
        add("PATCH", r"/function/python/" + NAME, function_update)
        add(
            "GET", r"/function/python/" + NAME,
            lambda m, b, q: (
                200,
                self.dataset.read_page(m.group("name"), **self._page_args(q)),
            ),
        )
        add(
            "DELETE", r"/function/python/" + NAME,
            lambda m, b, q: (
                self.function.delete(m.group("name")),
                (200, {"result": "deleted"}),
            )[1],
        )

        # ---- Observe (the reference's separate-repo watch service) ----
        def observe_wait(m, body, query):
            name = m.group("name")
            try:
                timeout = float(query.get("timeout", 30))
            except (TypeError, ValueError):
                raise BadRequest("timeout must be a number")
            self.ctx.require_existing(name)
            import time as _time

            deadline = _time.time() + min(timeout, 300)
            while _time.time() < deadline:
                meta = self.ctx.artifacts.metadata.read(name)
                if meta.get("finished") or meta.get("jobState") == "failed":
                    return 200, {"metadata": meta}
                _time.sleep(0.1)
            return 200, {"metadata": self.ctx.artifacts.metadata.read(name)}

        # ---- Observe event feed + wildcard webhooks (before the NAME
        # routes: "events"/"webhook" would otherwise match as artifact
        # names; the dispatcher is first-match) ----
        def observe_events(m, body, query):
            try:
                since = int(query.get("sinceId", -1))
                limit = int(query.get("limit", 100))
            except (TypeError, ValueError):
                raise BadRequest("sinceId/limit must be integers")
            return 200, {"result": self.ctx.webhooks.events(since, limit)}

        add("GET", r"/observe/events", observe_events)

        def webhook_register_all(m, body, query):
            try:
                hook = self.ctx.webhooks.register(
                    "*", body.get("url"), body.get("events")
                )
            except ValueError as exc:
                raise ValidationError(str(exc)) from None
            return 201, {"result": hook}

        add("POST", r"/observe/webhook", webhook_register_all)
        add(
            "GET", r"/observe/webhook",
            lambda m, b, q: (200, {"result": self.ctx.webhooks.list("*")}),
        )
        add(
            "DELETE", r"/observe/webhook/(?P<hook>[0-9]+)",
            lambda m, b, q: (
                (200, {"result": "deleted"})
                if self.ctx.webhooks.unregister("*", int(m.group("hook")))
                else (404, {"error": "no such webhook"})
            ),
        )

        # Deliberate long-poll: exempt from the gateway deadline.
        add("GET", r"/observe/" + NAME, observe_wait, no_timeout=True)

        # ---- Observe push (webhooks on state transitions) ----
        def webhook_register(m, body, query):
            name = m.group("name")
            self.ctx.require_existing(name)
            try:
                hook = self.ctx.webhooks.register(
                    name, body.get("url"), body.get("events")
                )
            except ValueError as exc:
                raise ValidationError(str(exc)) from None
            # Registration raced the job: if the artifact is ALREADY
            # terminal, the engine's completion path has fired and
            # will never fire again — deliver now instead of leaving
            # the client waiting forever.  The metadata re-read comes
            # AFTER the hook insert: a job finishing in between sees
            # the hook (engine fires) OR we see the terminal state
            # (immediate fire) — both orders deliver; reading before
            # the insert would let the completion slip through the gap
            # unseen by either side.  (Worst case both fire — webhook
            # delivery is at-least-once, the standard contract.)
            meta = self.ctx.artifacts.metadata.read(name) or {}
            event = None
            if meta.get("jobState") == "failed":
                event = "failed"
            elif meta.get("finished"):
                event = "finished"
            if event is not None and event in hook["events"]:
                # deliver_to, not notify: the transition already hit
                # the event feed and wildcard hooks when it happened —
                # only THIS late registration needs the catch-up POST.
                self.ctx.webhooks.deliver_to(hook, name, event, meta)
                hook = {**hook, "firedImmediately": event}
            return 201, {"result": hook}

        def webhook_list(m, body, query):
            name = m.group("name")
            self.ctx.require_existing(name)
            return 200, {"result": self.ctx.webhooks.list(name)}

        def webhook_delete(m, body, query):
            ok = self.ctx.webhooks.unregister(
                m.group("name"), int(m.group("hook"))
            )
            if not ok:
                return 404, {"error": "no such webhook"}
            return 200, {"result": "deleted"}

        add("POST", rf"/observe/{NAME}/webhook", webhook_register)
        add("GET", rf"/observe/{NAME}/webhook", webhook_list)
        add("DELETE", rf"/observe/{NAME}/webhook/(?P<hook>[0-9]+)",
            webhook_delete)

        # ---- Job control plane (jobs/engine.py + jobs/journal.py) ----
        # DELETE cancels a queued job outright, or flips a RUNNING
        # job's CancelToken — the body observes it at its next
        # epoch/batch boundary, winds down like an early stop and the
        # engine records a journaled `cancelled` terminal state
        # (202: accepted, cooperative — poll the artifact).
        def job_cancel(m, body, query):
            name = m.group("name")
            self.ctx.require_existing(name)
            result = self.ctx.engine.cancel(name)
            if result is True:
                return 200, {"job": name, "result": "cancelled"}
            if result:
                return 202, {"job": name, "result": "cancelling"}
            return 409, {
                "error": f"job {name!r} is not queued or running "
                "(already terminal)"
            }

        add("DELETE", rf"/jobs/{NAME}", job_cancel)

        # ---- Introspection ----
        add(
            "GET", r"/registry",
            lambda m, b, q: (200, registry.list_registered()),
            cacheable=True,
        )
        add(
            "GET", r"/artifacts",
            lambda m, b, q: (
                200, self.dataset.list_metadata(q.get("type", ""))
            ),
        )
        add("GET", r"/health", lambda m, b, q: (200, {"status": "ok"}))

        def metrics_view(m, body, query):
            # Legacy JSON view, now backed by the same per-route
            # instrumentation that feeds the registry histograms.
            with self._metrics_lock:
                routes = {
                    k: {
                        **v,
                        "avg_ms": round(v["total_ms"] / v["count"], 3)
                        if v["count"] else 0.0,
                    }
                    for k, v in self._metrics.items()
                }
            return 200, {
                "routes": routes,
                "budget": {
                    "request_timeout_s":
                        self.config.api.request_timeout_s,
                    "cache_ttl_s": self.config.api.cache_ttl_s,
                },
            }

        # Per-route request counts/latencies — the krakend :8090
        # metrics exporter's role (SURVEY §5.1).
        add("GET", r"/metrics", metrics_view)

        # ---- Unified observability (obs/) ----
        def metrics_prom(m, body, query):
            """Prometheus text exposition over the whole registry:
            HTTP latency histograms, job queue waits, lease
            utilization, compile-cache counters, serving occupancy
            and store/replication state — one scrapeable surface
            unifying the four legacy JSON endpoints."""
            text = self.obs.render_prometheus()
            return 200, (
                "text/plain; version=0.0.4; charset=utf-8",
                text.encode(),
            )

        add("GET", r"/metrics\.prom", metrics_prom)

        def job_trace(m, body, query):
            """Span tree of a job's life (queue wait → lease →
            compile → per-epoch steps), read back from the newest
            execution-ledger record carrying a trace."""
            name = m.group("name")
            self.ctx.require_existing(name)
            doc = None
            for rec in reversed(
                self.ctx.artifacts.ledger.history(name)
            ):
                if rec.get("trace"):
                    doc = rec["trace"]
                    break
            if doc is None:
                return 404, {
                    "error": f"no trace recorded for {name!r} (job "
                             "still running, predates tracing, or "
                             "LO_TPU_OBS_TRACE=0)"
                }
            spans = doc.get("spans", [])
            return 200, {
                "name": name,
                "requestId": doc.get("requestId"),
                "droppedSpans": doc.get("droppedSpans", 0),
                "spans": spans,
                "tree": obs_tracing.span_tree(spans),
            }

        add("GET", rf"/observability/jobs/{NAME}/trace", job_trace)

        # ---- Windowed time-series rollups (obs/rollup.py) ----
        # The in-process time dimension: counter rates, gauge
        # min/avg/max and histogram-delta quantiles over the rollup
        # rings.  Query: ?name=<family>&windowS=<s>&points=<n> plus
        # any other key as a label filter (e.g. &model=mnist,
        # &route=POST+/serve/...); no name lists the tracked
        # families.
        def timeseries_view(m, body, query):
            name = query.get("name")
            try:
                window_s = float(query.get("windowS", 300.0))
                max_points = int(query.get("points", 0))
            except (TypeError, ValueError):
                raise ValidationError(
                    "windowS/points must be numeric"
                ) from None
            labels = {
                k: v for k, v in query.items()
                if k not in ("name", "windowS", "points")
            }
            return 200, self.rollup.timeseries(
                name, labels or None, window_s=window_s,
                max_points=max_points,
            )

        add("GET", r"/observability/timeseries", timeseries_view)

        # ---- SLO objectives + burn-rate alerts (obs/slo.py) ----
        # /alerts is the drill surface: pending/firing/resolved state
        # per (objective, instance) with the burn rates that produced
        # it; /slo is the objective/budget view.  Both mirror onto
        # /metrics.prom (lo_alert_active, lo_slo_burn_rate).
        add(
            "GET", r"/observability/alerts",
            lambda m, b, q: (200, self.slo.alerts()),
        )
        add(
            "GET", r"/observability/slo",
            lambda m, b, q: (200, self.slo.status()),
        )

        # Runtime objectives: the drill surface — POST an ad-hoc
        # objective (e.g. availability scoped to one route) before an
        # experiment, DELETE it after.  Config-built objectives are
        # the deployment's contract and stay non-removable.
        def slo_create(m, body, query):
            body = body or {}
            threshold_ms = body.get("thresholdMs")
            try:
                doc = self.slo.add_objective(
                    body.get("name"), body.get("kind"),
                    body.get("target", 0),
                    threshold_s=(
                        float(threshold_ms) / 1000.0
                        if threshold_ms is not None else None
                    ),
                    metric=body.get("metric"),
                    route=body.get("route"),
                )
            except (TypeError, ValueError) as exc:
                raise ValidationError(str(exc)) from None
            return 201, {"objective": doc}

        def slo_delete(m, body, query):
            name = m.group("name")
            if not self.slo.remove_objective(name):
                return 404, {
                    "error": f"no runtime objective {name!r}"
                }
            return 200, {"result": "deleted"}

        add("POST", r"/observability/slo", slo_create)
        add("DELETE", rf"/observability/slo/{NAME}", slo_delete)

        # ---- Flight recorder + debug bundles (obs/flight.py,
        # obs/bundle.py) ----
        # /flight is the live incident view: per-domain rings plus
        # the merged timeline.  /bundle (POST) freezes everything
        # into a durable on-disk bundle NOW; /bundles is the store.
        def flight_view(m, body, query):
            from learningorchestra_tpu.obs import flight as obs_flight

            domains = None
            if query.get("domain"):
                domains = tuple(
                    d for d in str(query["domain"]).split(",") if d
                )
            try:
                limit = int(query.get("limit", 0))
            except ValueError:
                raise ValidationError(
                    "limit must be an integer"
                ) from None
            doc = obs_flight.snapshot(domains=domains, limit=limit)
            doc["timeline"] = obs_flight.timeline(
                domains=domains, limit=limit
            )
            return 200, doc

        def bundle_create(m, body, query):
            body = body or {}
            reason = str(body.get("reason") or "manual")
            return 201, {
                "bundle": self.bundles.build(reason, {"via": "rest"})
            }

        def bundle_get(m, body, query):
            name = m.group("name")
            rel = query.get("file")
            if rel:
                # Retrieval: one bundle artifact's bytes (path
                # traversal is rejected inside read_file).
                return 200, (
                    "application/octet-stream",
                    self.bundles.read_file(name, rel),
                )
            doc = self.bundles.manifest(name)
            if doc is None:
                return 404, {"error": f"no bundle {name!r}"}
            return 200, doc

        def bundle_delete(m, body, query):
            name = m.group("name")
            if not self.bundles.delete(name):
                return 404, {"error": f"no bundle {name!r}"}
            return 200, {"result": "deleted"}

        add("GET", r"/observability/flight", flight_view)
        add("POST", r"/observability/bundle", bundle_create)
        add(
            "GET", r"/observability/bundles",
            lambda m, b, q: (200, self.bundles.status()),
        )
        add(
            "DELETE", r"/observability/bundles",
            lambda m, b, q: (
                200, {"deleted": self.bundles.delete_all()},
            ),
        )
        add("GET", rf"/observability/bundles/{NAME}", bundle_get)
        add("DELETE", rf"/observability/bundles/{NAME}",
            bundle_delete)

        # ---- On-demand profiler capture (obs/profiling.py) ----
        # start/stop wrap jax.profiler around a LIVE process: capture
        # a device trace while production traffic runs, list the
        # retained captures, pull the .xplane.pb artifacts for
        # offline TensorBoard analysis.  One capture at a time
        # (double-start → 409), auto-stop deadline, bounded dir.
        # NOTE: /start registered before /stop — the every-route-
        # metered gate dispatches in registration order, so its sweep
        # opens and then closes a capture instead of leaking one.
        def profile_start(m, body, query):
            body = body or {}
            return 201, {
                "capture": self.profiler.start(
                    name=body.get("name"),
                    max_seconds=body.get("maxSeconds"),
                )
            }

        def profile_stop(m, body, query):
            return 200, {"capture": self.profiler.stop()}

        add("POST", r"/observability/profile/start", profile_start)
        add("POST", r"/observability/profile/stop", profile_stop)
        add(
            "GET", r"/observability/profile",
            lambda m, b, q: (200, self.profiler.status()),
        )
        add(
            "GET", r"/observability/profile/captures",
            lambda m, b, q: (
                200, {"captures": self.profiler.list_captures()},
            ),
        )

        def profile_capture(m, body, query):
            name = m.group("name")
            rel = query.get("file")
            if rel:
                # Retrieval: one capture artifact's bytes (path
                # traversal is rejected inside read_file).
                return 200, (
                    "application/octet-stream",
                    self.profiler.read_file(name, rel),
                )
            doc = self.profiler.capture(name)
            if doc is None:
                return 404, {"error": f"no capture {name!r}"}
            return 200, doc

        add("GET", rf"/observability/profile/captures/{NAME}",
            profile_capture)
        add(
            "DELETE", rf"/observability/profile/captures/{NAME}",
            lambda m, b, q: (
                (200, {"result": "deleted"})
                if self.profiler.delete(m.group("name"))
                else (404, {"error": f"no capture {m.group('name')!r}"})
            ),
        )

        # ---- Cost accounting (obs/costs.py): the JSON view over the
        # per-program FLOPs/HBM ledger and the device-time ledgers
        # (per job / per model / per bucket) — the same numbers the
        # lo_program_* and lo_device_time_* Prometheus families carry.
        def costs_view(m, body, query):
            from learningorchestra_tpu.obs import costs as obs_costs

            return 200, obs_costs.snapshot()

        add("GET", r"/observability/costs", costs_view)

        # ---- Runtime lock witness (concurrency_rt.py) ----
        # The deadlock-diagnosis surface: witnessed acquisition-order
        # edges, held-while-blocking contention events, and every
        # currently held/contended lock with its holder, waiters and
        # their live thread stacks.  Meaningful under LO_TPU_WITNESS=1
        # (otherwise answers enabled=false with empty data — the
        # endpoint stays probeable either way).
        def locks_view(m, body, query):
            from learningorchestra_tpu import concurrency_rt

            return 200, concurrency_rt.snapshot(include_stacks=True)

        add("GET", r"/observability/locks", locks_view)

        # ---- Fault-injection plane (faults/plane.py) ----
        # The chaos drill's REST surface: inspect every registered
        # fault point, arm a seeded schedule against one, disarm one
        # or all.  Trigger counters also export at /metrics.prom
        # (lo_fault_triggers_total).
        def faults_status(m, body, query):
            return 200, faults.status()

        def faults_arm(m, body, query):
            body = body or {}
            mode = body.get("mode")
            if not mode:
                raise ValidationError(
                    f"missing 'mode' (one of {list(faults.MODES)})"
                )
            try:
                doc = faults.arm(
                    m.group("name"), str(mode),
                    rate=float(body.get("rate", 1.0)),
                    seed=int(body.get("seed", 0)),
                    after=int(body.get("after", 0)),
                    max_triggers=int(body.get("maxTriggers", 0)),
                    delay_ms=float(body.get("delayMs", 0.0)),
                )
            except (TypeError, ValueError) as exc:
                raise ValidationError(str(exc)) from None
            return 201, {"point": m.group("name"), "armed": doc}

        def faults_disarm(m, body, query):
            try:
                disarmed = faults.disarm(m.group("name"))
            except ValueError as exc:  # unknown point
                raise ValidationError(str(exc)) from None
            if not disarmed:
                return 404, {
                    "error": f"fault point {m.group('name')!r} is "
                             "not armed"
                }
            return 200, {"result": "disarmed"}

        add("GET", r"/faults", faults_status)
        add("DELETE", r"/faults",
            lambda m, b, q: (faults.disarm_all(),
                             (200, {"result": "disarmed"}))[1])
        add("POST", rf"/faults/{NAME}", faults_arm)
        add("DELETE", rf"/faults/{NAME}", faults_disarm)

        # ---- Ops status page (the reference's Portainer GUI role,
        # reference: docker-compose.yml:102-129): one human-readable
        # HTML view over the JSON the system already exposes — jobs,
        # fairness queues, chip leases, cluster agents, recent events.
        def status_view(m, body, query):
            return 200, ("text/html; charset=utf-8",
                         self._render_status().encode())

        add("GET", r"/status", status_view)

        # ---- Replication + HA peering (store/ha.py — the reference's
        # mongo replica set, reference: docker-compose.yml:42-90).
        # A network standby pulls WAL listings and byte ranges from
        # here, so the secondary replicates over the wire with no
        # shared mount (the mongo-secondary topology); the fence POST
        # lets a promoted standby demote a live-but-partitioned
        # primary; /replication/status carries the election epoch a
        # restarted node compares against its own before serving.
        from learningorchestra_tpu.store.ha import is_fenced
        from learningorchestra_tpu.store.replica import (
            FENCE_FILE,
            read_epoch,
        )

        def replication_wals(m, body, query):
            root = self.config.store.store_path()
            wals = []
            if root.is_dir():
                for wal in sorted(root.glob("*.wal")):
                    try:
                        wals.append(
                            {"name": wal.stem, "size": wal.stat().st_size}
                        )
                    except OSError:
                        continue  # dropped between glob and stat
            return 200, {
                "wals": wals,
                "epoch": read_epoch(root),
                "fenced": is_fenced(root) is not None,
            }

        add("GET", r"/replication/wals", replication_wals)

        def replication_wal_read(m, body, query):
            # NAME excludes "/" and "%", so the stem cannot traverse
            # out of the store root.
            root = self.config.store.store_path()
            offset = max(0, _int_param(query, "from", 0))
            length = _int_param(query, "len", 0)
            try:
                with open(root / f"{m.group('name')}.wal", "rb") as fh:
                    fh.seek(offset)
                    data = fh.read(length) if length > 0 else fh.read()
            except FileNotFoundError:
                return 404, {"error": f"no WAL {m.group('name')!r}"}
            return 200, ("application/octet-stream", data)

        add("GET", rf"/replication/wal/{NAME}", replication_wal_read)

        def replication_status(m, body, query):
            root = self.config.store.store_path()
            fence = is_fenced(root)
            return 200, {
                "role": "fenced" if fence is not None else "primary",
                "epoch": read_epoch(root),
                "fence": fence,
            }

        add("GET", r"/replication/status", replication_status)

        def replication_fence(m, body, query):
            root = self.config.store.store_path()
            # Same epoch discipline as every other demotion path: only
            # a STRICTLY HIGHER election epoch may fence this store.  A
            # stale standby from a prior election (or a replayed /
            # misdirected POST) must not take down a healthy primary.
            ours = read_epoch(root)
            theirs = int((body or {}).get("epoch", 0) or 0)
            if theirs <= ours:
                return 409, {
                    "error": f"fence epoch {theirs} is not newer than "
                             f"this store's epoch {ours}",
                    "epoch": ours,
                }
            root.mkdir(parents=True, exist_ok=True)
            (root / FENCE_FILE).write_text(
                json.dumps(dict(body or {}))
            )
            # Demote AFTER this response flushes: the caller (a
            # promoted standby) needs the acknowledgement, and the
            # fence watch would take up to an interval to notice.
            def demote():
                import time as _time

                _time.sleep(0.2)
                print(
                    "store fenced by peer over /replication/fence — "
                    "demoting: shutting down to prevent split-brain",
                    flush=True,
                )
                self.shutdown()

            threading.Thread(target=demote, daemon=True).start()
            return 200, {"fenced": True}

        add("POST", r"/replication/fence", replication_fence)

        # ---- scale-out control plane (jobs/cluster.py) ----

        def cluster_status(m, body, query):
            # Always 200 so ctx.cluster.status() works against any
            # topology: single-engine deployments report enabled=False
            # instead of a 404 the client would have to special-case.
            if self.ctx.cluster is None:
                doc = {"enabled": False, "engines": [], "claims": []}
            else:
                doc = {"enabled": True, **self.ctx.cluster.status()}
            if self.ctx.admission is not None:
                doc["tenants"] = self.ctx.admission.snapshot()
            return 200, doc

        add("GET", r"/cluster/status", cluster_status)

    # -- HTTP plumbing --------------------------------------------------------

    def _handle_raw(self, handler, m, body, query):
        try:
            # Chaos probe: an armed ``http.handler`` schedule can
            # delay or fail any admitted request — inside the try, so
            # an injected error exercises the real 500 path and an
            # injected delay the real gateway-timeout path.  For the
            # profiler routes this also proves an injected failure
            # fires BEFORE the handler claims the single-capture
            # lock — a chaos drill must not wedge profiling.
            faults.hit("http.handler")
            return handler(m, body, query)
        except (DuplicateArtifact, ConflictError,
                ProfilerConflict, BundleBusy) as exc:
            return 409, {"error": str(exc)}
        except (NotFoundError, ProfilerNotFound,
                BundleNotFound) as exc:
            return 404, {"error": str(exc)}
        except (ValidationError, RegistryError, ServeError,
                ProfilerError, BundleError) as exc:
            return 406, {"error": str(exc)}
        except LeaseTimeout as exc:
            # No chip lease within the placement budget: the pool is
            # saturated, not broken — same contract as the serving
            # tier's 429: explicit retry budget instead of a generic
            # 500 (Retry-After attached by the HTTP layer).
            return 503, {
                "error": str(exc),
                "retryAfter": self.config.serve.retry_after_s,
            }
        except QueueFull as exc:
            # Serving backpressure escaping ANY route (predict maps
            # it locally; a replicas POST racing shutdown lands here):
            # saturated/teardown, not broken — shed retriably.
            return 429, {
                "error": str(exc),
                "retryAfter": self.config.serve.retry_after_s,
            }
        except QuotaExceeded as exc:
            # Defense in depth: admission normally rejects in
            # _handle_slotted before the handler runs, but a handler
            # that submits extra jobs internally can still trip a
            # tenant quota mid-flight.
            return 429, {
                "error": str(exc),
                "retryAfter": exc.retry_after_s,
            }
        except (json.JSONDecodeError, BadRequest) as exc:
            return 400, {"error": f"bad JSON: {exc}"
                         if isinstance(exc, json.JSONDecodeError)
                         else str(exc)}
        except Exception as exc:  # pragma: no cover - defensive
            from learningorchestra_tpu.log import get_logger

            get_logger("api").exception("unhandled handler error: %r", exc)
            return 500, {"error": repr(exc)}

    @property
    def obs(self):
        """The registry this server currently exposes (collector
        registration guaranteed) — the process-wide one."""
        self._obs_handles()
        return self._obs_registry

    def _obs_handles(self):
        """HTTP metric handles on the current registry, rebinding (and
        re-registering the collector) if reset_registry() replaced it
        since the last use.  Double-checked under a lock: two racing
        requests must not register the collector twice."""
        reg = obs_metrics.get_registry()
        if reg is not self._obs_registry:
            with self._obs_rebind_lock:
                if reg is not self._obs_registry:
                    buckets_s = tuple(
                        ms / 1e3
                        for ms in self.config.obs.latency_buckets_ms
                    )
                    self._http_hist = reg.histogram(
                        "lo_http_request_duration_seconds",
                        "HTTP request latency by route.",
                        labels=("route",),
                        buckets=buckets_s,
                    )
                    self._http_total = reg.counter(
                        "lo_http_requests_total",
                        "HTTP requests by route and status class.",
                        labels=("route", "status"),
                    )
                    self._http_max = reg.gauge(
                        "lo_http_request_max_ms",
                        "Max observed request latency by route.",
                        labels=("route",),
                    )
                    reg.add_collector(self._collect_families)
                    self._obs_registry = reg
        return self._http_hist, self._http_total, self._http_max

    def _record_metric(self, key: str, status: int, dt_ms: float,
                       request_id: str | None = None) -> None:
        # Flight-recorder timeline entry FIRST (lock-free append).
        # The request id is threaded explicitly: this runs on the HTTP
        # thread, outside invoke()'s contextvar binding.
        from learningorchestra_tpu.obs import flight as obs_flight

        if request_id is not None:
            obs_flight.record(
                "http", "request", route=key, status=status,
                ms=round(dt_ms, 3), requestId=request_id,
            )
        else:
            obs_flight.record(
                "http", "request", route=key, status=status,
                ms=round(dt_ms, 3),
            )
        with self._metrics_lock:
            rec = self._metrics.setdefault(
                key,
                {"count": 0, "errors": 0, "total_ms": 0.0, "max_ms": 0.0},
            )
            rec["count"] += 1
            if status >= 400:
                rec["errors"] += 1
            rec["total_ms"] += dt_ms
            rec["max_ms"] = max(rec["max_ms"], dt_ms)
        # Registry mirror (obs/metrics.py): real latency HISTOGRAMS —
        # the avg/max dict above survives only as the legacy /metrics
        # JSON view's backing.  No-ops when LO_TPU_OBS_ENABLED=0.
        http_hist, http_total, http_max = self._obs_handles()
        http_hist.observe(dt_ms / 1e3, route=key)
        http_total.inc(
            route=key, status=f"{min(max(status // 100, 1), 5)}xx"
        )
        http_max.set_max(dt_ms, route=key)

    def _collect_families(self):
        """Pull-side exposition for GET /metrics.prom: snapshot the
        subsystems that already keep exact counters under their own
        locks — job queues, the chip-lease pool, the compiled-program
        cache, serving batchers, store WALs and replication state —
        into Prometheus families.  Runs at scrape time; must stay
        fast and must not throw (the renderer drops a failing
        collector's families, never the exposition)."""
        import time as _time

        from learningorchestra_tpu.obs.metrics import Family
        from learningorchestra_tpu.store.ha import is_fenced
        from learningorchestra_tpu.store.replica import read_epoch
        from learningorchestra_tpu.train import aot_store, compile_cache

        fams: list[Family] = []
        fams.append(
            Family(
                "gauge", "lo_uptime_seconds",
                "Seconds since this API process started.",
            ).sample(_time.time() - self._t_start)
        )

        # -- job engine: queue depth per fairness class ---------------
        depth = Family(
            "gauge", "lo_jobs_queue_depth",
            "Queued-but-undispatched jobs per fairness class.",
        )
        for cls, n in self.ctx.engine.queue_depths(
            include_empty=True
        ).items():
            depth.sample(n, job_class=cls)
        # Per-tenant breakdown rides the same family as extra samples
        # (labelled job_class + tenant) — emitted only once a tenant
        # has been seen, so single-tenant scrapes keep their shape.
        for (cls, tenant), n in (
            self.ctx.engine.queue_depths_by_tenant().items()
        ):
            depth.sample(n, job_class=cls, tenant=tenant or "-")
        fams.append(depth)

        # -- scale-out control plane ----------------------------------
        engines_live = 0
        if self.ctx.cluster is not None:
            try:
                cstat = self.ctx.cluster.status()
                engines_live = sum(
                    1 for e in cstat.get("engines", ()) if e.get("live")
                )
            except Exception:  # noqa: BLE001 — scrape must not fail
                engines_live = 0
        fams.append(
            Family(
                "gauge", "lo_cluster_engines",
                "Live job engines sharing this store "
                "(0 = clustering off).",
            ).sample(engines_live)
        )

        # -- chip-lease pool utilization ------------------------------
        snap = self.ctx.leaser.snapshot()
        n_all, n_free = len(snap["all"]), len(snap["free"])
        fams.append(
            Family(
                "gauge", "lo_lease_devices",
                "Chip-lease pool state (all/free/in_use).",
            )
            .sample(n_all, state="all")
            .sample(n_free, state="free")
            .sample(n_all - n_free, state="in_use")
        )

        # -- compiled-program cache -----------------------------------
        stats = compile_cache.get_cache().stats()
        events = Family(
            "counter", "lo_compile_cache_events_total",
            "Compiled-program cache lifetime counters.",
        )
        for kind in ("hits", "misses", "evictions", "coalesced"):
            events.sample(stats[kind], kind=kind)
        events.sample(
            stats["deviceInvalidations"], kind="device_invalidations"
        )
        fams.append(events)
        fams.append(
            Family(
                "counter", "lo_compile_cache_trace_seconds_total",
                "Cumulative seconds spent tracing/compiling programs.",
            ).sample(stats["traceTimeS"])
        )
        fams.append(
            Family(
                "gauge", "lo_compile_cache_entries",
                "Resident compiled-program cache entries.",
            ).sample(stats["entries"])
        )
        fams.append(
            Family(
                "gauge", "lo_compile_cache_bytes_estimate",
                "Estimated resident bytes of cached programs.",
            ).sample(stats["bytesEstimate"])
        )
        fams.append(
            Family(
                "gauge", "lo_compile_cache_measured_entries",
                "Cache entries charged at their MEASURED serialized "
                "size (vs the flat fallback estimate).",
            ).sample(stats.get("measuredEntries", 0))
        )

        # -- durable AOT executable store (train/aot_store.py) --------
        # Zeros when disabled (stats_snapshot keeps scrape shape
        # stable), so dashboards never see a series appear/vanish on a
        # config flip.
        aot = aot_store.stats_snapshot()
        fams.append(
            Family(
                "counter", "lo_compile_cache_aot_hits",
                "AOT executables restored from the durable store "
                "(dispatches that skipped trace AND compile).",
            ).sample(aot["hits"])
        )
        fams.append(
            Family(
                "counter", "lo_compile_cache_aot_misses",
                "Durable-store lookups with no usable blob.",
            ).sample(aot["misses"])
        )
        fams.append(
            Family(
                "counter", "lo_compile_cache_aot_load_errors",
                "Stale/corrupt AOT blobs that degraded to a live "
                "re-trace.",
            ).sample(aot["loadErrors"])
        )
        fams.append(
            Family(
                "gauge", "lo_compile_cache_aot_persisted_entries",
                "Executables currently persisted in the AOT store.",
            ).sample(aot["persistedEntries"])
        )
        fams.append(
            Family(
                "gauge", "lo_compile_cache_aot_persisted_bytes",
                "On-disk bytes of persisted AOT executables.",
            ).sample(aot["persistedBytes"])
        )

        # -- cost accounting: per-program FLOPs/HBM + device-time
        # attribution (obs/costs.py).  Cardinality is bounded by
        # construction: programs <= the cost ledger's cap (itself <=
        # program diversity the compile cache admits), jobs ride a
        # bounded freshest-N ring, buckets <= models x log2(max_batch).
        try:
            from learningorchestra_tpu.obs import costs as obs_costs

            fams += self._collect_cost_families(obs_costs)
        except Exception:  # noqa: BLE001 — cost families must never
            pass  # take down the whole exposition

        # -- serving: registry residency + batcher aggregates (the
        # same roll-up the tfevents snapshot uses — ONE aggregation,
        # serve/service.py aggregate()) ------------------------------
        sstats = self.serving.stats()
        agg = self.serving.aggregate(sstats)
        fams.append(
            Family(
                "gauge", "lo_serving_resident_models",
                "Models pinned resident on device.",
            ).sample(agg["resident_models"])
        )
        fams.append(
            Family(
                "gauge", "lo_serving_resident_bytes",
                "Parameter bytes pinned resident on device.",
            ).sample(agg["resident_bytes"])
        )
        sevents = Family(
            "counter", "lo_serving_events_total",
            "Serving lifetime counters, summed over served models.",
        )
        for kind in ("requests", "rows", "batches", "overflows",
                     "padded_rows"):
            sevents.sample(agg[kind], kind=kind)
        fams.append(sevents)
        fams.append(
            Family(
                "gauge", "lo_serving_queue_depth",
                "Rows queued across serving batchers.",
            ).sample(agg["queue_depth"])
        )
        fams.append(
            Family(
                "gauge", "lo_serving_batch_occupancy",
                "Mean dispatch occupancy (rows/bucket) over models.",
            ).sample(agg["occupancy"])
        )
        slat = Family(
            "gauge", "lo_serving_latency_ms",
            "Rolling request-latency quantiles (max over models).",
        )
        for q, val in agg["quantiles"].items():
            slat.sample(val, quantile=q)
        fams.append(slat)
        if sstats["models"]:
            # Per-model queue depth (fleet replicas summed): the
            # series the rollup engine tracks and the autoscaler's
            # growth-slope trigger fits against.  Cardinality <= the
            # registry's max_models cap.
            mdepth = Family(
                "gauge", "lo_serving_model_queue_depth",
                "Rows queued per served model (replicas summed).",
            )
            for model, mstats in sstats["models"].items():
                mdepth.sample(mstats["queueDepth"], model=model)
            fams.append(mdepth)

        # -- decode concurrency: live stream count and admission
        # headroom per resident-LM model, straight from the decoder's
        # own stats (free = unoccupied slots across its page pools —
        # the number of streams admittable without a pool grow).
        dstats = self.serving.decode.stats()
        if dstats["models"]:
            dactive = Family(
                "gauge", "lo_serving_decode_active_streams",
                "Streams active (queued+resident) per decode model.",
            )
            dfree = Family(
                "gauge", "lo_serving_decode_free_slots",
                "Unoccupied page-pool slots per decode model.",
            )
            for model, ds in dstats["models"].items():
                dactive.sample(ds["activeStreams"], model=model)
                dfree.sample(
                    sum(p["slots"] - p["live"] for p in ds["pools"]),
                    model=model,
                )
            fams += [dactive, dfree]

        # -- fleet: per-replica attribution.  Cardinality is bounded
        # by construction (models <= registry max_models, replicas <=
        # the per-model max bound, and replica indices are REUSED
        # lowest-free-first so scale oscillation cycles a fixed label
        # set instead of minting new ones), so these stay inside the
        # LO_TPU_OBS_MAX_SERIES budget without collapsing. -----------
        fleet = self.serving.fleet.snapshot()
        if fleet["models"]:
            nrepl = Family(
                "gauge", "lo_serving_replicas",
                "Active replicas per fleet-served model.",
            )
            rdepth = Family(
                "gauge", "lo_serving_replica_queue_depth",
                "Rows queued per replica batcher.",
            )
            rreq = Family(
                "counter", "lo_serving_replica_requests_total",
                "Requests routed per replica.",
            )
            for model, st in fleet["models"].items():
                nrepl.sample(st["size"], model=model)
                for r in st["replicas"]:
                    labels = {
                        "model": model,
                        "replica": str(r["replica"]),
                        "device": r["device"],
                    }
                    rdepth.sample(r["queueDepth"], **labels)
                    rreq.sample(r["requests"], **labels)
            fams += [nrepl, rdepth, rreq]
        if fleet["scaleTotals"]:
            # From the manager's CUMULATIVE totals, not the live sets:
            # a counter series must survive dissolve/invalidation
            # instead of vanishing or resetting mid-series.
            scale = Family(
                "counter", "lo_serving_fleet_scale_events_total",
                "Replica scale events per model and direction.",
            )
            for model, t in fleet["scaleTotals"].items():
                scale.sample(t["up"], model=model, direction="up")
                scale.sample(t["down"], model=model, direction="down")
            fams.append(scale)
        # Emitted even with no replica sets: the control loop keeps
        # ticking while fleets are drained away, and a counter that
        # vanishes mid-series breaks rate()/absence liveness alerts.
        fams.append(
            Family(
                "counter", "lo_serving_fleet_autoscaler_ticks_total",
                "Autoscaler control-loop passes.",
            ).sample(fleet["autoscaler"]["ticks"])
        )

        # -- store WALs + replication ---------------------------------
        root = self.config.store.store_path()
        wal_bytes, wal_files = 0, 0
        if root.is_dir():
            for wal in root.glob("*.wal"):
                try:
                    wal_bytes += wal.stat().st_size
                    wal_files += 1
                except OSError:
                    continue  # dropped between glob and stat
        fams.append(
            Family(
                "gauge", "lo_store_wal_bytes",
                "Total bytes across store WAL files.",
            ).sample(wal_bytes)
        )
        fams.append(
            Family(
                "gauge", "lo_store_wal_files",
                "Store WAL file count.",
            ).sample(wal_files)
        )
        fams.append(
            Family(
                "gauge", "lo_replication_epoch",
                "This store's election epoch.",
            ).sample(read_epoch(root))
        )
        fams.append(
            Family(
                "gauge", "lo_store_fenced",
                "1 when a standby fenced this store, else 0.",
            ).sample(1 if is_fenced(root) is not None else 0)
        )

        # -- rollup engine health + SLO burn/alert mirror -------------
        try:
            fams += self.rollup.prom_families()
            fams += self.slo.prom_families()
        except Exception:  # noqa: BLE001 — the mirror must never
            pass  # take down the whole exposition
        return fams

    def _collect_cost_families(self, obs_costs) -> list:
        """lo_program_* and lo_device_time_* / MFU families from the
        cost-accounting plane (obs/costs.py) — what each compiled
        program costs per execution, and who consumed the device."""
        from learningorchestra_tpu.obs.metrics import Family

        if not obs_costs.enabled():
            return []
        fams: list = []
        ledger = obs_costs.get_ledger().snapshot()
        programs = [p for p in ledger["programs"] if p["label"]]
        if programs:
            flops = Family(
                "gauge", "lo_program_flops",
                "XLA-reported FLOPs per execution of each compiled "
                "program.",
            )
            accessed = Family(
                "gauge", "lo_program_bytes_accessed",
                "XLA-reported bytes accessed per execution.",
            )
            hbm = Family(
                "gauge", "lo_program_hbm_bytes",
                "Per-program HBM footprint by kind "
                "(argument/output/temp/code).",
            )
            size = Family(
                "gauge", "lo_program_serialized_bytes",
                "Serialized executable size (what the compile cache's "
                "byte cap charges).",
            )
            for p in programs:
                # program + key: labels alone are NOT unique (two
                # fits of one architecture at different shapes share
                # a label string), and duplicate label sets would
                # make Prometheus reject the ENTIRE scrape — the
                # fingerprint prefix disambiguates.
                labels = {"program": p["label"], "key": p["key"]}
                if p["flops"] is not None:
                    flops.sample(p["flops"], **labels)
                if p["bytesAccessed"] is not None:
                    accessed.sample(p["bytesAccessed"], **labels)
                for kind, field in (
                    ("argument", "argumentBytes"),
                    ("output", "outputBytes"),
                    ("temp", "tempBytes"),
                    ("code", "generatedCodeBytes"),
                ):
                    if p[field] is not None:
                        hbm.sample(p[field], kind=kind, **labels)
                if p["serializedBytes"] is not None:
                    size.sample(p["serializedBytes"], **labels)
            fams += [f for f in (flops, accessed, hbm, size)
                     if f.samples]
        fams.append(
            Family(
                "counter", "lo_program_analyses_total",
                "Cost/memory analyses run at program build time.",
            )
            .sample(ledger["analyses"], outcome="ok")
            .sample(ledger["analysisFailures"], outcome="failed")
        )
        dt = obs_costs.devtime().snapshot(
            peak_flops=obs_costs.peak_flops()
        )
        totals = dt["totals"]
        fams.append(
            Family(
                "counter", "lo_device_time_seconds_total",
                "Attributed device seconds (sampled; scaled to be "
                "unbiased).",
            ).sample(totals["deviceTimeS"])
        )
        fams.append(
            Family(
                "counter", "lo_device_flops_total",
                "Attributed FLOPs across dispatches.",
            ).sample(totals["flops"])
        )
        if dt["jobs"]:
            jt = Family(
                "gauge", "lo_job_device_seconds",
                "Attributed device seconds per job (freshest-N ring).",
            )
            jmfu = Family(
                "gauge", "lo_job_mfu",
                "Model-FLOPs-utilization per job (needs "
                "LO_TPU_COSTS_PEAK_FLOPS).",
            )
            for job, doc in dt["jobs"].items():
                jt.sample(doc["deviceTimeS"], job=job)
                if "mfu" in doc:
                    jmfu.sample(doc["mfu"], job=job)
            fams.append(jt)
            if jmfu.samples:
                fams.append(jmfu)
        if dt["models"]:
            mt = Family(
                "gauge", "lo_model_device_seconds",
                "Attributed device seconds per served model.",
            )
            for model, doc in dt["models"].items():
                mt.sample(doc["deviceTimeS"], model=model)
            fams.append(mt)
        if dt["buckets"]:
            bmfu = Family(
                "gauge", "lo_serving_bucket_mfu",
                "Model-FLOPs-utilization per (model, bucket) (needs "
                "LO_TPU_COSTS_PEAK_FLOPS).",
            )
            bt = Family(
                "gauge", "lo_serving_bucket_device_seconds",
                "Attributed device seconds per (model, bucket).",
            )
            for key, doc in dt["buckets"].items():
                model, _, bucket = key.rpartition(":")
                bt.sample(doc["deviceTimeS"], model=model,
                          bucket=bucket)
                if "mfu" in doc:
                    bmfu.sample(doc["mfu"], model=model,
                                bucket=bucket)
            fams.append(bt)
            if bmfu.samples:
                fams.append(bmfu)
        return fams

    #: Route prefixes whose POST/PATCH enqueue engine jobs — the set
    #: per-tenant admission gates.  Serving routes (/serve/...) are
    #: deliberately absent: the batcher has its own QueueFull
    #: backpressure, and admin/observability mutations are not jobs.
    _JOB_ROUTE_PREFIXES = (
        "/dataset/", "/transform/", "/explore/", "/model/", "/train/",
        "/tune/", "/evaluate/", "/predict/", "/function/", "/builder/",
    )

    def _is_job_route(self, path: str) -> bool:
        prefix = self.config.api.api_prefix.rstrip("/")
        if prefix and path.startswith(prefix):
            path = path[len(prefix):]
        return path.startswith(self._JOB_ROUTE_PREFIXES)

    def handle(self, verb: str, path: str, body: dict, query: dict,
               idem_key: str | None = None,
               request_id: str | None = None,
               tenant: str | None = None):
        """Dispatch with the gateway budget enforced: request deadline
        (reference: krakend 10 s global timeout → 504), TTL response
        cache on opted-in GETs (300 s ``cache_ttl``), and per-route
        metrics (krakend's :8090 exporter → GET /metrics).

        ``idem_key`` (the X-Idempotency-Key header) makes a mutation
        replay-safe across store failover: a completed attempt's
        response is recorded in the store and handed back to retries
        instead of executing the handler twice.
        """
        import time as _time

        t0 = _time.perf_counter()
        if self._inflight is None:
            return self._handle_admitted(
                verb, path, body, query, t0, _Slot(None), idem_key,
                request_id, tenant,
            )
        if not self._inflight.acquire(blocking=False):
            # Saturated: shed load NOW rather than queue behind
            # max_inflight stuck handlers (a slow-loris of long POSTs
            # must not grow threads without bound).
            self._record_metric("saturated", 503, 0.0,
                                request_id=request_id)
            return 503, {
                "error": "gateway saturated "
                         f"({self.config.api.max_inflight} requests "
                         "in flight); retry with backoff"
            }
        return self._handle_admitted(
            verb, path, body, query, t0, _Slot(self._inflight),
            idem_key, request_id, tenant,
        )

    def _handle_admitted(self, verb, path, body, query, t0, slot,
                         idem_key=None, request_id=None, tenant=None):
        try:
            return self._handle_slotted(
                verb, path, body, query, t0, slot, idem_key,
                request_id, tenant,
            )
        finally:
            # The slot frees only when its LAST owner releases: for a
            # timed-out request the worker thread co-owns it, so an
            # abandoned handler keeps its slot until it really ends —
            # that's what keeps zombie threads BOUNDED by the cap.
            slot.release()

    def _handle_slotted(self, verb, path, body, query, t0, slot,
                        idem_key=None, request_id=None, tenant=None):
        import time as _time

        handler, m, route_key, flags = self.router.resolve(verb, path)
        if handler is None:
            status, payload = self.router.dispatch(verb, path, body, query)
            self._record_metric(
                route_key, status, (_time.perf_counter() - t0) * 1e3,
                request_id=request_id,
            )
            return status, payload

        # Per-tenant fair-share admission, checked at the gateway tier
        # BEFORE the handler runs: a rejected request must not leave an
        # orphan metadata document behind (the services write metadata
        # before submitting the job).
        if (
            self.ctx.admission is not None
            and verb in ("POST", "PATCH")
            and self._is_job_route(path)
        ):
            try:
                self.ctx.admission.check(tenant)
            except QuotaExceeded as exc:
                self._record_metric(
                    route_key, 429,
                    (_time.perf_counter() - t0) * 1e3,
                    request_id=request_id,
                )
                return 429, {
                    "error": str(exc),
                    "retryAfter": exc.retry_after_s,
                }

        ttl = self.config.api.cache_ttl_s
        cache_key = None
        if verb == "GET" and flags.get("cacheable") and ttl > 0:
            cache_key = (path, tuple(sorted(query.items())))
            with self._cache_lock:
                hit = self._cache.get(cache_key)
                if hit is not None and hit[0] > _time.monotonic():
                    self._record_metric(
                        route_key, hit[1],
                        (_time.perf_counter() - t0) * 1e3,
                        request_id=request_id,
                    )
                    return hit[1], hit[2]
        elif verb != "GET":
            # Any mutation invalidates the whole response cache — cheap
            # and safe (mutations are rare next to poll GETs).
            with self._cache_lock:
                self._cache.clear()

        idem_id = None
        if idem_key and verb in ("POST", "PATCH", "DELETE"):
            kind, *rest = self._idem_begin(
                idem_key,
                self._idem_fingerprint(verb, path, body, query),
            )
            if kind == "replay":
                status, payload = rest
                self._record_metric(
                    route_key, status,
                    (_time.perf_counter() - t0) * 1e3,
                    request_id=request_id,
                )
                return status, payload
            if kind == "mismatch":
                self._record_metric(
                    route_key, 422, (_time.perf_counter() - t0) * 1e3,
                    request_id=request_id,
                )
                return 422, {
                    "error": "this idempotency key was already used "
                             "for a different request — keys identify "
                             "ONE logical mutation; mint a fresh key "
                             "per operation",
                    "idempotency_key": idem_key,
                }
            if kind == "ambiguous":
                self._record_metric(
                    route_key, 409, (_time.perf_counter() - t0) * 1e3,
                    request_id=request_id,
                )
                return 409, {
                    "error": "a previous attempt with this "
                             "idempotency key began but has no "
                             "recorded outcome (still in flight, or "
                             "the primary died mid-request) — inspect "
                             "the artifact's state before retrying "
                             "with a fresh key",
                    "idempotency_key": idem_key,
                }
            idem_id = rest[0]

        def invoke():
            # Bind the request id INSIDE invoke: on the timeout path
            # the handler runs on a fresh worker thread, which does not
            # inherit the HTTP thread's context — binding here covers
            # both the inline and the threaded execution, so a job
            # submitted anywhere below carries the id into its trace.
            token = (
                obs_tracing.set_request_id(request_id)
                if request_id else None
            )
            try:
                # The tenant rides a contextvar for the same reason as
                # the request id: engine.submit() below stamps it onto
                # the job without every service signature changing.
                with bind_tenant(tenant):
                    result = self._handle_raw(handler, m, body, query)
            finally:
                if token is not None:
                    obs_tracing.reset_request_id(token)
            if idem_id is not None:
                self._idem_finish(idem_id, *result)
            return result

        timeout = self.config.api.request_timeout_s
        if flags.get("no_timeout") or timeout <= 0:
            status, payload = invoke()
        else:
            # Per-request thread (NOT a shared pool: N stuck handlers
            # must not poison a fixed pool into serving only 504s). The
            # abandoned thread finishes on its own; Python offers no
            # safe cancellation, so a timed-out mutation may still
            # commit later — same semantics as any gateway timeout.
            box: dict = {}

            def _run():
                try:
                    box["result"] = invoke()
                finally:
                    slot.release()  # holds the slot until REALLY done

            slot.share()  # worker co-owns; slot frees on LAST release
            worker = threading.Thread(
                target=_run, name="gateway-req", daemon=True
            )
            worker.start()
            worker.join(timeout)
            if "result" in box:
                status, payload = box["result"]
            else:
                status, payload = 504, {
                    "error": f"request exceeded {timeout}s gateway budget"
                }

        if cache_key is not None and status < 400:
            with self._cache_lock:
                self._cache[cache_key] = (
                    _time.monotonic() + ttl, status, payload
                )
        self._record_metric(
            route_key, status, (_time.perf_counter() - t0) * 1e3,
            request_id=request_id,
        )
        return status, payload

    def serve_forever(self, host: str | None = None, port: int | None = None):
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            #: Client-supplied request ids must be header-safe and
            #: bounded; anything else gets a freshly minted id.
            _RID_RE = re.compile(r"[A-Za-z0-9_.\-]{1,64}")

            def _run(self, verb: str):
                # Request id: echo the client's X-Request-Id or mint
                # one — set BEFORE the drain check so even a 503
                # carries it.
                rid = (self.headers.get("X-Request-Id") or "").strip()
                if not self._RID_RE.fullmatch(rid):
                    rid = obs_tracing.new_request_id()
                self._request_id = rid
                if api._drain_if_shutting_down(self):
                    return
                parsed = urlparse(self.path)
                query = {
                    k: v[0] for k, v in parse_qs(parsed.query).items()
                }
                # Tenant identity for fair-share admission: same
                # header-safety rules as the request id, but a bad
                # value is a 400 (silently reassigning a tenant would
                # bill one tenant's jobs to another's quota).
                tenant = (self.headers.get("X-Tenant") or "").strip()
                if tenant and not self._RID_RE.fullmatch(tenant):
                    self._send(400, {
                        "error": "invalid X-Tenant header: expected "
                                 "1-64 chars of [A-Za-z0-9_.-]",
                    })
                    return
                body = {}
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    raw = self.rfile.read(length)
                    try:
                        body = json.loads(raw) if raw.strip() else {}
                    except json.JSONDecodeError:
                        self._send(400, {"error": "request body is not JSON"})
                        return
                status, payload = api.handle(
                    verb, parsed.path, body, query,
                    idem_key=self.headers.get("X-Idempotency-Key"),
                    request_id=rid,
                    tenant=tenant or None,
                )
                self._send(status, payload)

            def _send(self, status: int, payload):
                events = getattr(payload, "sse_events", None)
                if callable(events):
                    self._send_sse(status, payload, events)
                    return
                if (
                    isinstance(payload, tuple)
                    and len(payload) == 2
                    and isinstance(payload[1], (bytes, bytearray))
                ):
                    ctype, data = payload
                else:
                    ctype = "application/json"
                    data = json.dumps(payload, default=str).encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                rid = getattr(self, "_request_id", None)
                if rid:
                    # Echoed on EVERY response (including errors): the
                    # correlation key across logs, metadata and the
                    # job's span tree.
                    self.send_header("X-Request-Id", rid)
                if status in (429, 503) and isinstance(payload, dict) \
                        and payload.get("retryAfter") is not None:
                    # Backpressure contract (serving queue overflow,
                    # chip-lease timeout): clients honor the standard
                    # header, the JSON field carries the same value
                    # for non-HTTP consumers.
                    self.send_header(
                        "Retry-After", str(payload["retryAfter"])
                    )
                self.end_headers()
                self.wfile.write(data)

            def _send_sse(self, status: int, stream, events):
                """Server-sent-events body for a DecodeStream payload.
                No Content-Length is possible (the token count is not
                known up front), so under HTTP/1.1 the body is
                EOF-delimited: ``Connection: close`` and the handler
                drops keep-alive for this socket.  A broken pipe mid-
                stream IS the client-disconnect signal — it aborts the
                stream so the engine frees its KV pages at the next
                step boundary."""
                self.close_connection = True
                self.send_response(status)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-store")
                self.send_header("Connection", "close")
                rid = getattr(self, "_request_id", None)
                if rid:
                    self.send_header("X-Request-Id", rid)
                self.end_headers()
                try:
                    for name, doc in events():
                        chunk = (
                            f"event: {name}\n"
                            f"data: {json.dumps(doc, default=str)}\n\n"
                        )
                        self.wfile.write(chunk.encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError,
                        OSError):
                    abort = getattr(stream, "abort", None)
                    if callable(abort):
                        abort("client disconnected")

            def do_GET(self):
                self._run("GET")

            def do_POST(self):
                self._run("POST")

            def do_PATCH(self):
                self._run("PATCH")

            def do_DELETE(self):
                self._run("DELETE")

        host = host or self.config.api.host
        port = self.config.api.port if port is None else port
        httpd = _BoundedThreadingHTTPServer(
            (host, port), Handler,
            max_connections=self.config.api.max_connections,
        )
        # Publish under the shutdown lock: serve_forever runs on a
        # daemon thread (start_background), so a shutdown() racing
        # this construction window would otherwise read _httpd as
        # None, "stop" nothing, and leak a live accept loop — the
        # exact stale-primary window the fence demotion closes.
        with self._shutdown_lock:
            if self._shut_down:
                httpd.server_close()
                return
            self._httpd = httpd
        self._start_fence_watch()
        try:
            httpd.serve_forever()
        except Exception:
            # shutdown() can claim and close the listener between the
            # publish above and serve_forever() entering its poll loop
            # — the serve call then trips on the closed socket.  That
            # interleaving is a clean stop, not an error.
            with self._shutdown_lock:
                if self._shut_down:
                    return
            raise

    #: Seconds between fence checks (tests shrink it).
    FENCE_CHECK_INTERVAL_S = 5.0

    def _drain_if_shutting_down(self, handler) -> bool:
        """503+Connection:close for requests arriving on kept-alive
        connections after shutdown/demotion — the accept loop is gone,
        but HTTP/1.1 persistent connections would otherwise keep being
        served by their handler threads (the split-brain window the
        fence demotion exists to close)."""
        if not self._shutting_down.is_set():
            return False
        handler.close_connection = True
        handler._send(503, {"error": "server is shutting down"})
        return True

    def _start_fence_watch(self) -> None:
        """Self-demote if a standby fences this store while we serve.

        serve() refuses to START on a fenced store, but a RUNNING
        primary can be fenced underneath itself: a network partition
        makes the standby declare us dead and promote; when the
        partition heals, clients that never lost their connection
        would keep writing HERE while new ones write to the promoted
        replica — the split-brain the fence exists to prevent.  On a
        shared filesystem (where the fence write succeeds) the demoted
        primary notices within one check interval and stops serving;
        the supervisor's restart then hits serve()'s startup refusal.
        Without shared storage the same watch polls the HA peer's
        /replication/status: a peer serving a HIGHER election epoch
        promoted over us — self-fence and demote (store/ha.py).
        """
        from learningorchestra_tpu.store.ha import is_fenced

        store_root = self.config.store.store_path()
        peer = self.config.ha.peer

        def watch():
            # wait() doubles as the sleep AND the exit signal: a
            # normal shutdown ends the thread promptly instead of
            # leaking one fence-poller per serve cycle.
            while not self._shutting_down.wait(
                self.FENCE_CHECK_INTERVAL_S
            ):
                fence = is_fenced(store_root)
                if fence is None and peer:
                    fence = _peer_supersedes(store_root, peer)
                if fence is not None:
                    print(
                        "store fenced while serving (promoted_to="
                        f"{fence.get('promoted_to')!r}) — demoting: "
                        "shutting down to prevent split-brain",
                        flush=True,
                    )
                    self.shutdown()
                    return

        threading.Thread(target=watch, daemon=True).start()

    def start_background(self, host: str = "127.0.0.1",
                         port: int | None = None) -> int:
        """Start on a daemon thread; returns the bound port (None/0 picks
        an ephemeral one)."""
        if port in (None, 0):
            import socket

            sock = socket.socket()
            sock.bind((host, 0))
            port = sock.getsockname()[1]
            sock.close()
        self._port = port
        threading.Thread(
            target=lambda: self.serve_forever(host=host, port=port),
            daemon=True,
        ).start()
        # Wait until the socket accepts.
        import socket as _socket
        import time as _time

        deadline = _time.time() + 10
        while _time.time() < deadline:
            try:
                with _socket.create_connection((host, port), timeout=0.2):
                    break
            except OSError:
                _time.sleep(0.02)
        return port

    def shutdown(self) -> None:
        """Idempotent stop: accept loop halted, LISTENING SOCKET
        CLOSED (reconnecting clients get an immediate refusal — what
        triggers their failover retry — instead of hanging in the
        kernel backlog), kept-alive connections answered 503+close by
        the dispatch gate, resources released once."""
        with self._shutdown_lock:
            if self._shut_down:
                return
            self._shut_down = True
            # Claim the listener under the same lock serve_forever
            # publishes it with: a shutdown racing the daemon-thread
            # construction either sees the httpd (and stops it) or
            # flips _shut_down first (and serve_forever refuses to
            # serve) — never a leaked accept loop.
            httpd, self._httpd = self._httpd, None
        self._shutting_down.set()
        # The registry outlives this server (process-global): drop the
        # collector so scrapes never touch a closed context.
        if self._obs_registry is not None:
            self._obs_registry.remove_collector(self._collect_families)
        # Stop the rollup/SLO clock: a demoted or stopped node must
        # not keep evaluating objectives over frozen windows (or
        # paging a webhook).  The singleton survives — a later
        # APIServer's construction re-arms the daemon.
        self.rollup.stop()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        self.profiler.close()
        self.serving.close()
        self.monitoring.close()
        self.ctx.close()


def _peer_supersedes(store_root, peer: str) -> dict | None:
    """Did the HA peer promote over this store?  Returns the fence
    record (after writing it locally, best-effort) when the peer is a
    primary serving a STRICTLY HIGHER election epoch, else None.

    This is the no-shared-disk half of fencing: the standby couldn't
    write our marker and the fence POST hit a dead process, so the
    epoch comparison is what stops the stale side.  An unreachable
    peer means "not superseded"; so does a peer answering
    ``role="standby"`` — a MONITORING standby serves its status route
    pre-promotion (store/ha.py _start_standby_status), which is why
    the check below requires ``role == "primary"``, not merely a
    response.
    """
    from learningorchestra_tpu.store.ha import peer_status
    from learningorchestra_tpu.store.replica import (
        FENCE_FILE,
        read_epoch,
    )

    status = peer_status(peer)
    if (
        status is None
        or status.get("role") != "primary"
        or int(status.get("epoch", 0)) <= read_epoch(store_root)
    ):
        return None
    fence = {
        "promoted_to": peer,
        "epoch": status.get("epoch"),
        "reason": "peer holds higher election epoch",
    }
    try:
        # Durable self-fence: the supervisor's restart refuses at
        # startup without another peer round-trip.
        store_root.mkdir(parents=True, exist_ok=True)
        (store_root / FENCE_FILE).write_text(json.dumps(fence))
    except OSError:
        pass
    return fence


def serve(config: Config | None = None) -> None:
    from learningorchestra_tpu.store.ha import (
        is_fenced,
        promotion_record,
        run_standby,
    )

    from pathlib import Path as _Path

    config = config or get_config()
    store_root = config.store.store_path()
    rejoin_root = _Path(str(store_root) + ".rejoined")

    def standby_of(target: str) -> None:
        # The ONE run_standby parameterization every rejoin path uses.
        # With a promotion record in rejoin_root this short-circuits
        # into resuming as primary; otherwise it monitors `target`
        # with the conservative rejoin takeover window (ha.rejoin_*:
        # an ordinary partner restart must never get fenced out).
        run_standby(
            target, None, rejoin_root, config.api.port,
            host=config.api.host,
            check_interval=config.ha.rejoin_interval_s,
            max_misses=config.ha.rejoin_misses,
        )

    def archive_stale_rejoin(reason: str) -> bool:
        # A stale .rejoined directory must move ASIDE, not merely be
        # ignored: run_standby treats a leftover .promoted record in
        # the replica root as "resume as primary", so a later rejoin
        # flow reusing the root would serve the stale history the
        # moment the real primary was unreachable.  Never delete —
        # the bytes stay for the operator.
        dst = rejoin_root.with_name(rejoin_root.name + ".stale")
        n = 0
        while dst.exists():
            n += 1
            dst = rejoin_root.with_name(f"{rejoin_root.name}.stale{n}")
        try:
            rejoin_root.rename(dst)
        except OSError as exc:
            print(
                f"stale rejoin replica {rejoin_root} ({reason}) could "
                f"not be archived ({exc}) — refusing to serve rather "
                "than risk resuming from it; move the directory away "
                "and restart.",
                flush=True,
            )
            return False
        print(
            f"archived stale rejoin replica to {dst} ({reason})",
            flush=True,
        )
        return True

    # A previous auto-rejoin cycle may already have PROMOTED this node
    # back to primary (partner died after we rejoined): the rejoined
    # replica — not the long-fenced original store — is then the
    # system of record, and a supervisor restart must resume serving
    # it, never re-stand-by for a dead partner.
    rejoin_rec = (
        promotion_record(rejoin_root) if config.ha.auto_rejoin else None
    )
    fence = is_fenced(store_root)
    if rejoin_rec:
        from learningorchestra_tpu.store.replica import read_epoch

        rejoin_epoch = read_epoch(rejoin_root)
        try:
            fence_epoch = int((fence or {}).get("epoch"))
        except (TypeError, ValueError):
            # Unreadable/malformed fence record: SOMEONE fenced the
            # store at an unknown epoch.  Every other is_fenced
            # consumer fails safe on this sentinel — so does the
            # comparison below (unknown ≠ "old").
            fence_epoch = None
        # The rejoin replica only shadows the original store while it
        # holds the HIGHEST election epoch this node knows of.  Two
        # ways it can be stale: an operator restored the original
        # store as system of record (fence cleared, epoch caught up),
        # or a LATER promotion fenced the original at an epoch beyond
        # the rejoin promotion's — either way resuming from the
        # replica would serve superseded history.
        if fence is None and read_epoch(store_root) >= rejoin_epoch:
            if not archive_stale_rejoin(
                "original store restored as system of record at an "
                "equal-or-higher epoch"
            ):
                return
        elif fence is not None and (
            fence_epoch is None or fence_epoch >= rejoin_epoch
        ):
            if not archive_stale_rejoin(
                "a later promotion fenced the original store at "
                + (
                    f"epoch {fence_epoch}, past"
                    if fence_epoch is not None
                    else "an UNKNOWN epoch (unreadable fence record — "
                         "failing safe), possibly past"
                )
                + f" the rejoin epoch {rejoin_epoch}"
            ):
                return
        else:
            print(
                "resuming as primary from the promoted rejoin replica "
                f"{rejoin_root}", flush=True,
            )
            standby_of(
                config.ha.peer or rejoin_rec.get("old_primary")
                or "127.0.0.1:0"
            )
            return

    if fence is None and config.ha.peer:
        fence = _peer_supersedes(store_root, config.ha.peer)
    if fence is not None:
        # A standby promoted itself over this store: serving from it
        # now would split-brain the cluster.
        new_primary = fence.get("promoted_to") or config.ha.peer
        if config.ha.auto_rejoin and new_primary:
            # Mongo's stepped-down primary rejoins as a SECONDARY on
            # its own: become the new primary's standby, shipping its
            # WALs over the network into a fresh replica root — the
            # pair regains redundancy with no operator action, and if
            # the new primary later dies, THIS node promotes and
            # serves on its original address again.
            print(
                "store is fenced — auto-rejoining as a standby of "
                f"{new_primary} (replica: {rejoin_root})",
                flush=True,
            )
            standby_of(new_primary)
            return
        # Exit CLEANLY so the supervisor's restart-on-failure loop
        # ends instead of resurrecting a fenced primary (store/ha.py).
        hint = (
            "auto-rejoin is ON but no rejoin target could be "
            "determined (unreadable fence marker and no LO_HA_PEER) — "
            "fix the pairing or re-join manually."
            if config.ha.auto_rejoin
            else "Re-join by running this node as a standby of the "
                 "new primary, or set LO_HA_AUTO_REJOIN=1 to do this "
                 "automatically."
        )
        print(
            "store is fenced — a standby promoted itself to "
            f"{fence.get('promoted_to') or 'a new primary'}; refusing "
            f"to serve. {hint}",
            flush=True,
        )
        return
    APIServer(config).serve_forever()


if __name__ == "__main__":
    serve()


def _int_param(query: dict, key: str, default: int) -> int:
    try:
        return int(query.get(key, default))
    except (TypeError, ValueError):
        raise BadRequest(f"{key} must be an integer")
