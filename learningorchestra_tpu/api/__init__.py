"""REST API layer.

One HTTP front server replaces the reference's KrakenD gateway + nine
Flask containers (SURVEY §1 L1-L2): the full public route table of
``microservices/krakend/krakend.json`` (~110 endpoints under
``/api/learningOrchestra/v1``) served by a single threaded process over
the service layer.
"""

from learningorchestra_tpu.api.server import APIServer, Router, serve

__all__ = ["APIServer", "Router", "serve"]
