#!/usr/bin/env bash
# One-command LOCAL cluster bring-up with restart-on-failure supervision
# — the container-less analogue of `docker compose up` above and of the
# reference's `run.sh` (reference: run.sh:32 `docker stack deploy`,
# docker-compose.yml:3-6 restart policy).
#
#   deploy/run_local.sh [N_AGENTS]
#
# Env: LO_TPU_API_PORT (default 8080), LO_COORD_PORT (default 7070),
#      LO_TPU_STORE_ROOT / LO_TPU_VOLUME_ROOT (default ./lo-data/...).
# Stops the whole cluster on Ctrl-C / SIGTERM.

set -u

N_AGENTS="${1:-2}"
API_PORT="${LO_TPU_API_PORT:-8080}"
COORD_PORT="${LO_COORD_PORT:-7070}"
DATA_ROOT="${LO_DATA_ROOT:-$PWD/lo-data}"
export LO_TPU_API_PORT="$API_PORT"
export LO_TPU_STORE_ROOT="${LO_TPU_STORE_ROOT:-$DATA_ROOT/store}"
export LO_TPU_VOLUME_ROOT="${LO_TPU_VOLUME_ROOT:-$DATA_ROOT/volumes}"
# Cluster mode: POST /train/horovod fans out to the agents below
# (LO_CLUSTER_MODE=0 keeps fits in-process in the API server).
if [ "${LO_CLUSTER_MODE:-1}" = "1" ] && [ "$N_AGENTS" -ge 2 ]; then
  export LO_TPU_TASK_COORDINATOR="127.0.0.1:$COORD_PORT"
  export LO_TPU_WORLD_SIZE="$N_AGENTS"
fi
mkdir -p "$LO_TPU_STORE_ROOT" "$LO_TPU_VOLUME_ROOT"

PIDS=()

# Supervise: restart the role if it exits non-zero (the reference's
# on-failure policy); clean exit (0) ends supervision.  Each supervisor
# runs in its OWN process group (setsid) so cleanup can kill the whole
# tree — background subshells share the script's pgid, and killing just
# the subshell would orphan the python service it spawned.
supervise() {
  local name="$1"; shift
  local cmd
  printf -v cmd '%q ' "$@"
  setsid bash -c '
    while true; do
      '"$cmd"'
      code=$?
      if [ "$code" -eq 0 ]; then
        echo "['"$name"'] exited cleanly" >&2
        break
      fi
      echo "['"$name"'] exited with $code — restarting in 1s" >&2
      sleep 1
    done
  ' &
  PIDS+=($!)
}

cleanup() {
  echo "stopping cluster" >&2
  for pid in "${PIDS[@]}"; do
    kill -- -"$pid" 2>/dev/null || kill "$pid" 2>/dev/null || true
  done
  # Bounded grace, then KILL the groups: the supervisors live in
  # their OWN process groups (setsid), unreachable from a caller's
  # killpg on THIS script — if cleanup stalls on a saturated box and
  # the caller SIGKILLs us mid-wait, un-KILLed groups would orphan
  # their services (observed: a coordinator+api+agent trio surviving
  # a test teardown for an hour, stealing a core's worth of probes).
  for _ in $(seq 1 20); do
    alive=0
    for pid in "${PIDS[@]}"; do
      kill -0 "$pid" 2>/dev/null && alive=1
    done
    [ "$alive" = 0 ] && break
    sleep 0.5
  done
  for pid in "${PIDS[@]}"; do
    kill -9 -- -"$pid" 2>/dev/null || true
  done
  wait 2>/dev/null
  exit 0
}
trap cleanup INT TERM

supervise coordinator python -m learningorchestra_tpu coordinator \
  --host 127.0.0.1 --port "$COORD_PORT"
# Port on the command line (redundant with LO_TPU_API_PORT) so the
# process is identifiable by pgrep/pkill for teardown sweeps.
supervise api python -m learningorchestra_tpu serve --port "$API_PORT"
# Store HA (LO_HA_STANDBY=1): a warm standby ships the primary's WALs
# and promotes itself on sustained health-check failure — the mongo
# replica set's automatic election (store/ha.py).  A fenced old
# primary's restart exits cleanly, ending its supervision loop.
if [ "${LO_HA_STANDBY:-0}" = "1" ]; then
  STANDBY_PORT="${LO_HA_STANDBY_PORT:-$((API_PORT + 1))}"
  # Generous takeover window (2 s x 15 = 30 s dead, matching the
  # compose manifest): a supervised api restart pays ~10 s of python
  # imports, which must read as a blip, not a dead primary.
  #
  # LO_HA_TRANSPORT=http ships WALs over the primary's /replication
  # routes instead of reading its store directory — the no-shared-
  # storage mode compose/k8s use (store/ha.py); the default reads
  # through the filesystem, which on ONE host is the same disk anyway.
  STORE_ARGS=()
  if [ "${LO_HA_TRANSPORT:-fs}" != "http" ]; then
    STORE_ARGS=(--primary-store "$LO_TPU_STORE_ROOT")
  fi
  supervise standby python -m learningorchestra_tpu standby \
    --primary "127.0.0.1:$API_PORT" \
    ${STORE_ARGS[@]+"${STORE_ARGS[@]}"} \
    --replica "$DATA_ROOT/store-replica" \
    --port "$STANDBY_PORT" --host 127.0.0.1 \
    --interval 2 --misses 15
fi
for i in $(seq 1 "$N_AGENTS"); do
  supervise "agent$i" python -m learningorchestra_tpu agent \
    --coordinator "127.0.0.1:$COORD_PORT" --id "agent$i"
done

echo "cluster up: api=:$API_PORT coordinator=:$COORD_PORT agents=$N_AGENTS" >&2
wait
