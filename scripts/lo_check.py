#!/usr/bin/env python
"""lochecks CLI — the repo's first-party static-analysis suite.

Usage::

    python scripts/lo_check.py learningorchestra_tpu/
    python scripts/lo_check.py learningorchestra_tpu/ --no-drift
    python scripts/lo_check.py --rules          # rule catalog

Exit code 0 = no unsuppressed error findings (warn findings never
fail the run — they are worklists).  Suppress a finding inline with
``# lo-check: disable=<rule>`` on (or directly above) its line, or
``# lo-check: disable-file=<rule>`` for a whole file.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from learningorchestra_tpu.analysis.runner import (  # noqa: E402
    RULES,
    run_checks,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="learningorchestra_tpu static-analysis suite"
    )
    parser.add_argument(
        "package", nargs="?", default="learningorchestra_tpu",
        help="package root to analyze",
    )
    parser.add_argument(
        "--repo-root", default=None,
        help="repo root for cross-artifact drift gates "
        "(default: parent of the package root)",
    )
    parser.add_argument(
        "--no-drift", action="store_true",
        help="skip the cross-artifact drift gates",
    )
    parser.add_argument(
        "--whole-program", action="store_true",
        help="also compose the per-module lock models into the "
        "global graph (cross-module inversions, blocking-call-"
        "under-lock, make_lock name congruence)",
    )
    parser.add_argument(
        "--witness", default=None, metavar="DUMP_JSON",
        help="cross-check a runtime witness snapshot "
        "(LO_TPU_WITNESS_DUMP output) against the static whole-"
        "program graph (implies --whole-program)",
    )
    parser.add_argument(
        "--rules", action="store_true", help="print the rule catalog",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also list suppressed findings",
    )
    args = parser.parse_args(argv)

    if args.rules:
        width = max(len(r) for r in RULES)
        for rule, (severity, desc) in sorted(RULES.items()):
            print(f"{rule:<{width}}  {severity:<5}  {desc}")
        return 0

    report = run_checks(
        args.package,
        repo_root=args.repo_root,
        drift=not args.no_drift,
        whole_program=args.whole_program or args.witness is not None,
        witness_dump=args.witness,
    )
    for path, message in report.parse_errors:
        print(f"{path}: PARSE ERROR: {message}")
    for finding in report.findings:
        print(finding.render())
    if args.show_suppressed:
        for finding in report.suppressed:
            print(f"[suppressed] {finding.render()}")
    print(
        f"lo_check: {report.files_scanned} files, "
        f"{len(report.errors)} error(s), "
        f"{len(report.warnings)} warning(s), "
        f"{len(report.suppressed)} suppressed"
    )
    return report.exit_code()


if __name__ == "__main__":
    sys.exit(main())
