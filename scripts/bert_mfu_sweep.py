"""BERT-base MFU sweep on chip — VERDICT r3 item 2 (27% → 40%+).

Sweeps (batch, seq, remat, flash) over the bf16 BertModel train step
and prints samples/s + MFU per point.  Run when the tunnel is up:

    PYTHONPATH=/root/.axon_site:/root/repo python scripts/bert_mfu_sweep.py

All timing uses the looped methodology (TPU_EVIDENCE.md): K vs 3K fused
epochs in single dispatches, differenced, so the tunnel's round-trip
latency cancels.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

assert jax.devices()[0].platform == "tpu", jax.devices()
print("device:", jax.devices()[0], flush=True)

from bench import (  # noqa: E402 — repo root on PYTHONPATH
    _fused_throughput,
    _model_flops_per_sample,
    _peak_flops,
)
from learningorchestra_tpu.models.text import BertModel  # noqa: E402

PEAK = _peak_flops("tpu")
rng = np.random.default_rng(0)

_p = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
assert float(jnp.sum(jax.jit(lambda a: a @ a)(_p))) != 0
print("probe matmul ok; lowering HLO check next", flush=True)

# One-time: prove the TRAIN path really lowers to the Pallas flash
# kernel on chip (VERDICT r3 item 2's "not mha_reference" check) —
# Mosaic kernels appear as tpu_custom_call in the HLO.
_est = BertModel(max_len=128, num_layers=1)
_tok = jnp.asarray(rng.integers(0, 30522, (1, 128), dtype=np.int32))
_est._init_params(_tok)
_hlo = jax.jit(_est.module.apply).lower(_est.params, _tok).as_text()
print(json.dumps({
    "check": "flash_in_train_path",
    "tpu_custom_call": "tpu_custom_call" in _hlo or "CustomCall" in _hlo,
}), flush=True)

# (seq, bs) grid: seq 128 is the BASELINE config-4 shape; 512 is where
# the flash kernel pays off in-model.  bs rows chosen to bracket the
# HBM limit of one v5e chip for BERT-base + adam.
GRID = [
    (128, 16), (128, 32), (128, 64), (128, 128), (128, 256),
    (512, 8), (512, 16), (512, 32),
]
# At seq 128 the flash kernel's tiling overhead can lose to XLA's own
# fused attention — measure the use_flash=False point where it might:
# picking the faster attention per shape is a legitimate MFU lever.
FLASH_OFF_POINTS = {(128, 32), (128, 64), (128, 128), (128, 256),
                    (512, 16)}


def _variants(seq, bs):
    out = [(False, None), (True, None), ("dots", None)]
    if (seq, bs) in FLASH_OFF_POINTS:
        out.append((False, False))
    return out


results = []
for seq, bs in GRID:
    for remat, use_flash in _variants(seq, bs):
        n = max(4 * bs, 256)
        tok = rng.integers(0, 30522, (n, seq), dtype=np.int32)
        lab = rng.integers(0, 2, (n,), dtype=np.int32)
        est = BertModel(max_len=seq, remat=remat, use_flash=use_flash)
        est._init_params(jnp.asarray(tok[:1]))
        per_sample = _model_flops_per_sample(est, jnp.asarray(tok[:1]))
        try:
            t0 = time.perf_counter()
            thr = _fused_throughput(est, tok, lab, bs, k=2)
            wall = time.perf_counter() - t0
        except Exception as exc:  # noqa: BLE001 — OOM points just report
            print(f"seq={seq} bs={bs} remat={remat} "
                  f"flash={use_flash}: FAILED {exc!r}", flush=True)
            continue
        mfu = thr * per_sample / PEAK if per_sample else 0.0
        row = {
            "seq": seq, "bs": bs, "remat": remat,
            "use_flash": use_flash,
            "samples_per_sec": round(thr, 1), "mfu": round(mfu, 4),
            "wall_s": round(wall, 1),
        }
        results.append(row)
        print(json.dumps(row), flush=True)

best = max(results, key=lambda r: r["mfu"], default=None)
print("BEST:", json.dumps(best), flush=True)
