"""Sub-minute on-chip evidence grab — runs BEFORE tpu_quick_evidence.

The 2026-08-01 tunnel window lasted ~3 minutes: long enough to answer a
probe and compile ONE small model, not long enough for the two-model
quick-evidence script (its 51 MB MNIST upload + four fused-epoch
compiles overran the window and the RPC hung when the tunnel dropped).
This stage banks the single highest-value number — bf16 MNIST-CNN
train throughput on silicon, the headline continuity metric every
BENCH_r0N.json carries — with the smallest possible on-chip footprint:
one model, 4k samples (12.8 MB upload), two fused-epoch compiles.

    PYTHONPATH=/root/.axon_site:/root/repo python scripts/tpu_flash_evidence.py

Methodology matches bench.py `_fused_throughput` (k vs 3k fused epochs,
differenced, so tunnel round-trips cancel) so the number is directly
comparable with TPU_EVIDENCE.md and the full bench suite.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

assert jax.devices()[0].platform == "tpu", jax.devices()
print("device:", jax.devices()[0], flush=True)
print("start:", time.strftime("%H:%M:%S"), flush=True)

t0 = time.perf_counter()
_p = jnp.asarray(np.ones((128, 128), np.float32))
assert float(jnp.sum(jax.jit(lambda a: a @ a)(_p))) > 0
print(f"probe ok in {time.perf_counter()-t0:.2f}s", flush=True)

from bench import (  # noqa: E402 — repo root on PYTHONPATH
    _fused_throughput,
    _model_flops_per_sample,
    _peak_flops,
)
from learningorchestra_tpu.models.vision import MnistCNN  # noqa: E402

rng = np.random.default_rng(0)
x = rng.standard_normal((4096, 28, 28, 1)).astype(np.float32)
y = rng.integers(0, 10, (4096,), dtype=np.int32)

est = MnistCNN()
est._init_params(jnp.asarray(x[:1]))
t0 = time.perf_counter()
thr = _fused_throughput(est, x, y, 1024, k=2)
per = _model_flops_per_sample(est, jnp.asarray(x[:1]))
print(json.dumps({
    "model": "mnist_cnn_bf16_flash", "batch": 1024, "n": 4096,
    "samples_per_sec": round(thr, 1),
    "mfu": round(thr * per / _peak_flops("tpu"), 4) if per else None,
    "measure_s": round(time.perf_counter() - t0, 1),
}), flush=True)
print("FLASH EVIDENCE DONE", flush=True)
