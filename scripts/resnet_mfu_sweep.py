"""ResNet-50 MFU sweep on chip — VERDICT r2 weak #3 (14% MFU, f32-era).

Sweeps (batch, remat) over the bf16 ResNet-50 train step at 224x224 and
prints samples/s + MFU per point.  Run when the tunnel is up:

    PYTHONPATH=/root/.axon_site:/root/repo python scripts/resnet_mfu_sweep.py

Timing uses the fused-epoch methodology (TPU_EVIDENCE.md): K vs 3K
epochs in single dispatches, differenced, so tunnel round-trip latency
cancels.  remat=True trades ~1 forward of FLOPs for O(blocks) less
activation HBM — the knob that unlocks bs >= 256 at 224x224.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

assert jax.devices()[0].platform == "tpu", jax.devices()
print("device:", jax.devices()[0], flush=True)

from bench import (  # noqa: E402 — repo root on PYTHONPATH
    _fused_throughput,
    _model_flops_per_sample,
    _peak_flops,
)
from learningorchestra_tpu.models.vision import ResNet50  # noqa: E402

PEAK = _peak_flops("tpu")
rng = np.random.default_rng(0)

_p = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
assert float(jnp.sum(jax.jit(lambda a: a @ a)(_p))) != 0
print("probe matmul ok; sweep next", flush=True)

# (bs, remat, s2d_stem): the s2d points measure ROOFLINE.md's stem
# prediction — the classic conv7×7 stem wastes >90% of the MXU lanes
# on C_in=3; space-to-depth folds it into a ≥128-deep contraction.
GRID = [
    (64, False, False), (128, False, False), (128, False, True),
    (128, True, False), (256, True, False), (256, True, True),
    (512, True, False),
]

results = []
for bs, remat, s2d in GRID:
    n = 2 * bs
    x = rng.standard_normal((n, 224, 224, 3)).astype(np.float32)
    y = rng.integers(0, 1000, (n,), dtype=np.int32)
    est = ResNet50(remat=remat, s2d_stem=s2d)
    est._init_params(jnp.asarray(x[:1]))
    per_sample = _model_flops_per_sample(est, jnp.asarray(x[:1]))
    try:
        t0 = time.perf_counter()
        thr = _fused_throughput(est, x, y, bs, k=2)
        wall = time.perf_counter() - t0
    except Exception as exc:  # noqa: BLE001 — OOM points just report
        print(f"bs={bs} remat={remat} s2d={s2d}: FAILED {exc!r}",
              flush=True)
        continue
    mfu = thr * per_sample / PEAK if per_sample else 0.0
    row = {
        "bs": bs, "remat": remat, "s2d_stem": s2d,
        "samples_per_sec": round(thr, 1), "mfu": round(mfu, 4),
        "wall_s": round(wall, 1),
    }
    results.append(row)
    print(json.dumps(row), flush=True)

best = max(results, key=lambda r: r["mfu"], default=None)
print("BEST:", json.dumps(best), flush=True)
