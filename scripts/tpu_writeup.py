"""Post-chain evidence writeup: tpu_chain_logs/*.log → TPU_EVIDENCE.md.

The watch chain (scripts/tpu_watch.sh) banks each on-chip stage's raw
output under tpu_chain_logs/.  This script distills them into a
machine-generated section of TPU_EVIDENCE.md (managed between marker
comments, idempotent — rerunning replaces the section) so a tunnel
window that opens AFTER the build session has ended still leaves
readable evidence, not just raw logs.  The watch loop runs it after
every completed stage and commits.
"""

from __future__ import annotations

import json
import re
from datetime import datetime, timezone
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
LOGDIR = REPO / "tpu_chain_logs"
EVIDENCE = REPO / "TPU_EVIDENCE.md"
BEGIN = "<!-- AUTO-ONCHIP-BEGIN (scripts/tpu_writeup.py) -->"
END = "<!-- AUTO-ONCHIP-END -->"

STAGES = [
    ("tpu_flash_evidence", "Flash evidence (sub-minute headline)"),
    ("tpu_obs_evidence", "Observability overhead probe"),
    ("tpu_flight_evidence", "Flight-recorder append-cost probe"),
    ("tpu_warmboot_evidence", "Warm-boot probe (AOT cache vs cold trace)"),
    ("tpu_mpmd_evidence", "MPMD pipeline probe (per-stage programs vs monolithic)"),
    ("tpu_decode_evidence", "Streaming decode probe (continuous batching vs solo)"),
    ("tpu_cluster_evidence",
     "Control-plane claim-path probe (share of a minimal dispatch)"),
    ("tpu_recovery_smoke", "Kill-9 recovery drill (journal resume)"),
    ("tpu_quick_evidence", "Quick evidence (headline numbers)"),
    ("tpu_validate_r2", "Round-2 backlog validation"),
    ("tpu_validate_r3", "Round-3 backlog validation"),
    ("bert_mfu_sweep", "BERT-base MFU sweep"),
    ("resnet_mfu_sweep", "ResNet-50 MFU sweep"),
    ("bench", "bench.py (multi-model suite)"),
]


def _json_rows(path: Path) -> list[str]:
    rows = []
    try:
        for line in path.read_text(errors="replace").splitlines():
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                try:
                    json.loads(line)
                except ValueError:
                    continue
                rows.append(line)
            elif line.startswith("BEST:"):
                rows.append(line)
    except OSError:
        pass
    return rows


def _cluster_highlight(rows: list[str]) -> list[str]:
    """Surface the control-plane acceptance number from bench's row:
    claim-path overhead as a fraction of a minimal dispatch
    (bench.py `_claim_probe`, banked under the `cluster` key)."""
    for line in reversed(rows):
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if not isinstance(doc, dict):
            continue
        # bench banks the probe under "cluster"; the dedicated
        # tpu_cluster_evidence stage prints it at top level.
        probe = doc.get("cluster")
        if not isinstance(probe, dict):
            probe = doc
        if (
            isinstance(probe, dict)
            and "claim_share_of_dispatch_pct" in probe
        ):
            return [
                f"Claim-path overhead: {probe.get('claim_us')} us/claim "
                f"= {probe['claim_share_of_dispatch_pct']}% of a "
                "minimal dispatch (acceptance bar: <= 5%); full "
                f"claim+release cycle {probe.get('cycle_us')} us.",
                "",
            ]
    return []


def build_section() -> str:
    stamp = datetime.now(timezone.utc).strftime("%Y-%m-%d %H:%M UTC")
    out = [BEGIN,
           f"## On-chip results banked by the watch chain ({stamp})",
           "",
           "Generated from `tpu_chain_logs/*.log` by"
           " `scripts/tpu_writeup.py`; raw logs are committed alongside.",
           ""]
    any_rows = False
    for stem, title in STAGES:
        rows = _json_rows(LOGDIR / f"{stem}.log")
        if not rows:
            continue
        any_rows = True
        out.append(f"### {title}")
        out.append("")
        out.append("```")
        out.extend(rows[-60:])  # sweeps print one row per point
        out.append("```")
        out.append("")
        if stem in ("bench", "tpu_cluster_evidence"):
            out.extend(_cluster_highlight(rows))
    if not any_rows:
        out.append("_No stage has produced results yet._")
        out.append("")
    out.append(END)
    return "\n".join(out)


def main() -> None:
    section = build_section()
    try:
        text = EVIDENCE.read_text()
    except FileNotFoundError:
        text = "# TPU hardware evidence\n"
    if BEGIN in text and END in text:
        text = re.sub(
            re.escape(BEGIN) + ".*?" + re.escape(END),
            lambda _m: section,
            text,
            flags=re.S,
        )
    else:
        text = text.rstrip() + "\n\n" + section + "\n"
    EVIDENCE.write_text(text)
    print("TPU_EVIDENCE.md section updated")


if __name__ == "__main__":
    main()
