"""On-box observability-overhead evidence: run bench._obs_probe and
print its JSON — dispatch throughput with the obs layer on vs off plus
the direct per-job cost breakdown (trace lifecycle, metric ops, ledger
trace write).  Short stage (~1-2 min): the probe is host-side, so it
banks a number whether or not the TPU tunnel stays up, but running it
in the chain records the number for the SAME box and build the other
stages measure.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bench import _obs_probe  # noqa: E402


def main() -> None:
    result = {"obs": _obs_probe()}
    overhead = result["obs"]["overhead_pct"]
    # Loud verdict line for the watch log; the JSON is the record.
    print(
        f"obs overhead {overhead}% "
        f"({'OK' if overhead < 5.0 else 'REGRESSION: >= 5%'})",
        file=sys.stderr, flush=True,
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
