"""On-box streaming-decode evidence: run bench._decode_probe and print
its JSON — continuous-batching engine throughput vs sequential solo
decode, mid-flight-admission TTFT, and bit-identity of engine output
against the solo path.  Short stage (~2-3 min): trains one tiny decoder
LM, then times best-of-3 on both paths on whatever backend is up, so it
records the speedup for the SAME box and build the other stages measure.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bench import _decode_probe  # noqa: E402


def main() -> None:
    result = {"decode": _decode_probe()}
    speedup = result["decode"]["continuous_batching_speedup"]
    identical = result["decode"]["bit_identical_to_solo"]
    # Loud verdict line for the watch log; the JSON is the record.
    verdict = "OK" if (speedup >= 2.0 and identical) else "REGRESSION"
    print(
        f"decode continuous-batching speedup {speedup}x, "
        f"bit_identical={identical} ({verdict}: need >= 2.0x + identical)",
        file=sys.stderr, flush=True,
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
