#!/bin/bash
# Probe the axon TPU tunnel; the moment it answers a real dispatch,
# fire the on-chip validation chain in order.  Each stage gets its own
# timeout so a mid-script tunnel drop can't wedge the chain — on a
# stage failure we fall back to probing and re-run the FAILED stage
# when the tunnel returns (stages are idempotent).
#
# Usage: bash scripts/tpu_watch.sh  (logs to <repo>/tpu_chain_logs/ —
# IN the repo so a chain that completes after the session ends still
# leaves its evidence where the next commit picks it up)
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH=/root/.axon_site:/root/repo
# Persistent compilation cache: the tunnel flaps, and every retry repays
# its compiles from scratch otherwise.  If the axon backend can't
# serialize executables this is a harmless no-op warning.
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=3
LOGDIR="$(pwd)/tpu_chain_logs"
mkdir -p "$LOGDIR"

# Static-analysis gate FIRST: it needs no tunnel, costs ~4 s, and a
# tree failing its own lock/JAX/drift contracts should not spend
# tunnel windows banking evidence for code that can't merge.
# --whole-program adds the cross-module lock-order graph +
# blocking-call-under-lock + witness-name congruence checks.
if ! timeout 120 python -u scripts/lo_check.py learningorchestra_tpu/ \
        --whole-program \
        > "$LOGDIR/lo_check.log" 2>&1; then
    echo "$(date -u +%H:%M:%S) lo_check FAILED — fix findings before \
watching (see $LOGDIR/lo_check.log)" | tee -a "$LOGDIR/watch.log"
    exit 1
fi
echo "$(date -u +%H:%M:%S) lo_check clean" >> "$LOGDIR/watch.log"

probe() {
    # 40 s: an UP tunnel answers this in ~5 s (init + tiny matmul);
    # 90 s only stretched the down-state retry cycle to 135 s —
    # longer than some observed windows.
    timeout 40 python -u -c "
import jax, numpy as np, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu'
x = jax.device_put(np.ones((128, 128), np.float32))
assert float(jnp.sum(jax.jit(lambda a: a @ a)(x))) > 0
print('PROBE_OK')
" 2>/dev/null | grep -q PROBE_OK
}

# Stages run in ASCENDING expected-runtime order (the :timeout suffix
# doubles as the runtime estimate): observed tunnel windows are short
# (~3 min to tens of minutes), so every window should bank the
# shortest remaining stages first instead of starving them behind a
# long sweep that the window can't fit anyway.  Flash-evidence leads —
# it banks ONE number (bf16 MNIST throughput, the headline continuity
# metric) in under a minute of tunnel time; of the two equal-budget
# 3600s stages, bench.py goes first because it banks the round's
# headline record while resnet_mfu_sweep only refines a rider.
STAGES=(
  "scripts/tpu_flash_evidence.py:300"
  "scripts/tpu_obs_evidence.py:300"
  "scripts/tpu_flight_evidence.py:300"
  "scripts/tpu_warmboot_evidence.py:300"
  "scripts/tpu_mpmd_evidence.py:300"
  "scripts/tpu_decode_evidence.py:300"
  "scripts/tpu_cluster_evidence.py:300"
  "scripts/tpu_recovery_smoke.py:600"
  "scripts/tpu_quick_evidence.py:900"
  "scripts/tpu_validate_r2.py:2700"
  "scripts/tpu_validate_r3.py:2700"
  "bench.py:3600"
  "scripts/resnet_mfu_sweep.py:3600"
  "scripts/bert_mfu_sweep.py:5400"
)
declare -A DONE
declare -A FAILS
declare -A DROPFAILS
MAX_FAILS=4   # a deterministic script bug must not loop forever
# Drop-coincident failures are normally free retries (the dominant
# failure mode is a mid-run tunnel drop), but a stage that fails
# deterministically right as the tunnel flaps would otherwise retry
# forever and block every later stage: after this many CONSECUTIVE
# uncounted failures, charge one real attempt.  Deliberate trade-off:
# a healthy stage whose runtime exceeds EVERY tunnel window is
# indistinguishable from a deterministic failure and will eventually
# be charged too — yielding to the later (shorter) stages is the
# lesser loss; 12 consecutive mid-run drops with zero completions is
# already a written-off window.
MAX_DROPFAILS=3

while true; do
    all_done=1
    for s in "${STAGES[@]}"; do
        name="${s%%:*}"
        [ "${DONE[$name]:-0}" = 1 ] && continue
        all_done=0
        if ! probe; then
            echo "$(date -u +%H:%M:%S) tunnel down (next: $name)" >> "$LOGDIR/watch.log"
            # 45 s, not 120: observed windows are ~3 min — a 2 min
            # probe gap can eat most of one.
            sleep 45
            continue 2
        fi
        tmo="${s##*:}"
        log="$LOGDIR/$(basename "$name" .py).log"
        # Rotate per attempt: the writeup must distill ONLY the final
        # (successful) attempt's rows, not stale rows from an aborted
        # run appended above them; the failed attempt stays readable.
        [ -f "$log" ] && mv "$log" "$log.prev"
        echo "$(date -u +%H:%M:%S) RUN $name" >> "$LOGDIR/watch.log"
        if timeout "$tmo" python -u "$name" >> "$log" 2>&1; then
            DONE[$name]=1
            echo "$(date -u +%H:%M:%S) DONE $name" >> "$LOGDIR/watch.log"
            # Bank immediately: distill logs into TPU_EVIDENCE.md and
            # commit (pathspec-scoped so a concurrent build session's
            # staged files are never swept in), so a window that
            # outlives the build session still leaves committed,
            # readable evidence.  Retries ride out a concurrent
            # session's index.lock; on final failure the banked paths
            # are UNSTAGED so a later unrelated commit can't sweep
            # them in.
            python scripts/tpu_writeup.py >> "$LOGDIR/watch.log" 2>&1 || true
            banked=0
            for _try in 1 2 3; do
                if git add tpu_chain_logs TPU_EVIDENCE.md \
                        >> "$LOGDIR/watch.log" 2>&1 \
                   && git commit -q \
                        -m "Bank on-chip evidence: $(basename "$name" .py) completed" \
                        -- tpu_chain_logs TPU_EVIDENCE.md \
                        >> "$LOGDIR/watch.log" 2>&1; then
                    banked=1
                    break
                fi
                sleep 2
            done
            if [ "$banked" = 0 ]; then
                echo "$(date -u +%H:%M:%S) BANK COMMIT FAILED for $name (left unstaged)" >> "$LOGDIR/watch.log"
                git reset -q -- tpu_chain_logs TPU_EVIDENCE.md 2>/dev/null || true
            fi
        else
            rc=$?
            # Only deterministic failures count toward GIVE UP: if the
            # tunnel is down right after the failure, the stage almost
            # certainly died to a mid-run drop (the dominant failure
            # mode — ~3-minute windows), and burning one of 4 attempts
            # on it would eventually abandon a perfectly good script.
            if probe; then
                DROPFAILS[$name]=0
                FAILS[$name]=$(( ${FAILS[$name]:-0} + 1 ))
                echo "$(date -u +%H:%M:%S) FAIL $name (rc=$rc, attempt ${FAILS[$name]}/$MAX_FAILS)" >> "$LOGDIR/watch.log"
                if [ "${FAILS[$name]}" -ge "$MAX_FAILS" ]; then
                    DONE[$name]=1
                    echo "$(date -u +%H:%M:%S) GIVE UP $name" >> "$LOGDIR/watch.log"
                fi
            else
                DROPFAILS[$name]=$(( ${DROPFAILS[$name]:-0} + 1 ))
                if [ "${DROPFAILS[$name]}" -ge "$MAX_DROPFAILS" ]; then
                    # N consecutive drop-coincident failures: stop
                    # assuming the tunnel, charge a real attempt so a
                    # deterministically failing stage eventually
                    # yields to the stages behind it.
                    DROPFAILS[$name]=0
                    FAILS[$name]=$(( ${FAILS[$name]:-0} + 1 ))
                    echo "$(date -u +%H:%M:%S) FAIL $name (rc=$rc) during tunnel drop — $MAX_DROPFAILS consecutive, counted (attempt ${FAILS[$name]}/$MAX_FAILS)" >> "$LOGDIR/watch.log"
                    if [ "${FAILS[$name]}" -ge "$MAX_FAILS" ]; then
                        DONE[$name]=1
                        echo "$(date -u +%H:%M:%S) GIVE UP $name" >> "$LOGDIR/watch.log"
                    fi
                else
                    echo "$(date -u +%H:%M:%S) FAIL $name (rc=$rc) during tunnel drop — not counted (${DROPFAILS[$name]}/$MAX_DROPFAILS)" >> "$LOGDIR/watch.log"
                fi
            fi
            sleep 30
            continue 2
        fi
    done
    [ "$all_done" = 1 ] && break
done
echo "$(date -u +%H:%M:%S) CHAIN COMPLETE" >> "$LOGDIR/watch.log"
