"""Minimal on-chip evidence grab — the FIRST thing to run in a tunnel
window.  The tunnel has been flapping in ~minute-long windows; the full
validation chain needs 10+ minutes of it.  This script gets the round's
two headline numbers (bf16 MNIST-CNN and BERT-base train throughput +
MFU, the BENCH/BASELINE configs 2 and 4) in one short run so even a
brief window banks the evidence that matters most.

    PYTHONPATH=/root/.axon_site:/root/repo python scripts/tpu_quick_evidence.py

Timing is the fused-epoch methodology (TPU_EVIDENCE.md): k vs 3k epochs
as single dispatches, differenced, so tunnel round-trips cancel.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

assert jax.devices()[0].platform == "tpu", jax.devices()
print("device:", jax.devices()[0], flush=True)


def step(name):
    print(f"STEP {name} @ {time.strftime('%H:%M:%S')}", flush=True)


step("probe")
rng = np.random.default_rng(0)
_p = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
t0 = time.perf_counter()
assert float(jnp.sum(jax.jit(lambda a: a @ a)(_p))) != 0
print(f"probe matmul ok in {time.perf_counter()-t0:.2f}s", flush=True)

from bench import (  # noqa: E402 — repo root on PYTHONPATH
    _fused_throughput,
    _model_flops_per_sample,
    _peak_flops,
)

PEAK = _peak_flops("tpu")

# BERT first: the flash stage already banks an MNIST number, so a
# window long enough for only one model here should spend it on the
# MFU-relevant BERT measurement (BASELINE config 4's shape).
# -- BERT-base seq128, bf16, bs 32 (config 4's shape) -----------------
from learningorchestra_tpu.models.text import BertModel  # noqa: E402

step("bert-base bf16 seq128 bs32")
tok = rng.integers(0, 30522, (2048, 128), dtype=np.int32)
lab = rng.integers(0, 2, (2048,), dtype=np.int32)
bert = BertModel(max_len=128)
bert._init_params(jnp.asarray(tok[:1]))
thr = _fused_throughput(bert, tok, lab, 32, k=2)
per = _model_flops_per_sample(bert, jnp.asarray(tok[:1]))
print(json.dumps({
    "model": "bert_base_bf16_seq128", "batch": 32,
    "samples_per_sec": round(thr, 1),
    "mfu": round(thr * per / PEAK, 4) if per else None,
}), flush=True)

# -- MNIST-CNN, bf16, bs 1024 (the headline continuity metric) --------
from learningorchestra_tpu.models.vision import MnistCNN  # noqa: E402

step("mnist bf16 bs1024")
x = rng.standard_normal((16384, 28, 28, 1)).astype(np.float32)
y = rng.integers(0, 10, (16384,), dtype=np.int32)
est = MnistCNN()
est._init_params(jnp.asarray(x[:1]))
thr = _fused_throughput(est, x, y, 1024, k=4)
per = _model_flops_per_sample(est, jnp.asarray(x[:1]))
print(json.dumps({
    "model": "mnist_cnn_bf16", "batch": 1024,
    "samples_per_sec": round(thr, 1),
    "mfu": round(thr * per / PEAK, 4) if per else None,
}), flush=True)

print("QUICK EVIDENCE DONE", flush=True)
