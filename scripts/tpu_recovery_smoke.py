"""Recovery smoke drill: boot → submit → kill -9 → recover → assert
resumed.

The on-chip twin of tests/test_journal_recovery.py's kill-9 drill,
shaped as a tpu_watch.sh stage: an orchestrator child process boots a
ServiceContext over a scratch store, submits a 6-epoch checkpointed
train fit, and SIGKILLs ITSELF once the managed checkpoint tree
reaches step >= 2 (a seeded `train.epoch` delay guarantees the kill
lands mid-fit); a second child boots over the same store — journal
replay re-dispatches the fit through the checkpoint-resume path — and
reports the resumed run's epoch spans.  PASS means: jobState
`finished`, engine epoch 2, first resumed epoch >= 2 and strictly
fewer epoch spans than a from-scratch run.

Runs on whatever backend the environment provides (the tunnel'd TPU
on the watch box; CPU anywhere else) — the journal/recovery plane is
backend-agnostic, the stage just proves it against the real wiring.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_CHILD_ORCHESTRATOR = r"""
import json, os, signal, sys, time
import numpy as np
from learningorchestra_tpu import faults
from learningorchestra_tpu.config import Config
from learningorchestra_tpu.services.context import ServiceContext
from learningorchestra_tpu.services.executor import ExecutorService
from learningorchestra_tpu.services.model import ModelService

cfg = Config.from_env()
cfg.store.backend = "python"
ctx = ServiceContext(cfg)
model = ModelService(ctx)
ex = ExecutorService(ctx)
rng = np.random.default_rng(0)
x = rng.standard_normal((64, 8)).astype("float32")
y = (x.sum(1) > 0).astype("int32")
model.create(
    "m", module_path="learningorchestra_tpu.models.mlp",
    class_name="MLPClassifier",
    class_parameters={"hidden_layer_sizes": [8], "num_classes": 2},
)
ctx.engine.wait("m", timeout=300)
faults.arm("train.epoch", "delay", delay_ms=500, after=2)
ex.create(
    "fit1", parent_name="m", method="fit",
    method_parameters={
        "x": x.tolist(), "y": y.tolist(), "epochs": 6,
        "checkpoint_every": 1, "checkpoint_min_interval_s": 0,
        "checkpoint_async": False,
    },
    artifact_type="train/tensorflow",
)
marker = ctx.checkpoint_dir("fit1") / "latest.json"
deadline = time.time() + 300
while time.time() < deadline:
    try:
        if json.loads(marker.read_text()).get("step", 0) >= 2:
            break
    except (OSError, ValueError):
        pass
    time.sleep(0.02)
else:
    print("NO_CHECKPOINT", flush=True)
    sys.exit(3)
print("KILLING", flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""

_CHILD_RECOVERY = r"""
import json, time
from learningorchestra_tpu.config import Config
from learningorchestra_tpu.services.context import ServiceContext

cfg = Config.from_env()
cfg.store.backend = "python"
ctx = ServiceContext(cfg)
deadline = time.time() + 300
meta = {}
while time.time() < deadline:
    meta = ctx.artifacts.metadata.read("fit1") or {}
    if meta.get("finished") or meta.get("jobState") == "failed":
        break
    time.sleep(0.1)
hist = ctx.artifacts.ledger.history("fit1")
trace = next(
    (r.get("trace") for r in reversed(hist) if r.get("trace")), None
)
epochs = sorted(
    s["attrs"]["epoch"]
    for s in (trace or {}).get("spans", [])
    if s.get("name") == "epoch"
)
print("RESULT " + json.dumps({
    "jobState": meta.get("jobState"),
    "engineEpoch": meta.get("engineEpoch"),
    "epochs": epochs,
}), flush=True)
ctx.close()
"""


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="lo_recovery_smoke_")
    env = dict(os.environ)
    env.update({
        "LO_TPU_STORE_ROOT": os.path.join(tmp, "store"),
        "LO_TPU_VOLUME_ROOT": os.path.join(tmp, "vol"),
    })
    env.pop("LO_TPU_WITNESS", None)

    print("recovery-smoke: phase 1 — boot, submit, kill -9 mid-fit")
    first = subprocess.run(
        [sys.executable, "-c", _CHILD_ORCHESTRATOR],
        env=env, capture_output=True, text=True, timeout=540,
    )
    if first.returncode != -signal.SIGKILL:
        print(first.stdout[-4000:])
        print(first.stderr[-4000:])
        print(f"FAIL: orchestrator exited rc={first.returncode} "
              "(expected SIGKILL)")
        return 1
    t0 = time.time()
    print("recovery-smoke: phase 2 — restart, replay journal, resume")
    second = subprocess.run(
        [sys.executable, "-c", _CHILD_RECOVERY],
        env=env, capture_output=True, text=True, timeout=540,
    )
    if second.returncode != 0 or "RESULT " not in second.stdout:
        print(second.stdout[-4000:])
        print(second.stderr[-4000:])
        print(f"FAIL: recovery child rc={second.returncode}")
        return 1
    result = json.loads(
        second.stdout.split("RESULT ", 1)[1].splitlines()[0]
    )
    epochs = result.get("epochs") or []
    ok = (
        result.get("jobState") == "finished"
        and result.get("engineEpoch") == 2
        and epochs
        and min(epochs) >= 2
        and max(epochs) == 5
        and len(epochs) < 6
    )
    print(json.dumps({
        "recovery_smoke": result,
        "recover_wall_s": round(time.time() - t0, 1),
        "resumed_from_epoch": min(epochs) if epochs else None,
    }))
    if not ok:
        print(f"FAIL: {result}")
        return 1
    print("recovery-smoke: PASS — resumed from epoch "
          f"{min(epochs)}, finished under engine epoch 2")
    return 0


if __name__ == "__main__":
    sys.exit(main())
