"""On-chip validation of round-2 additions — run when the TPU tunnel is up.

Covers: causal/sliding-window flash timing + correctness (looped), ring-
flash sp=1 composition, RoPE/GQA/window decode, KV-cache generate, a MoE
train step, and the fused-epoch bench runner.

Ordered cheapest-compile first: the tunnel flaps, and a hang mid-script
should still leave the maximum recorded evidence (the 04:01 UTC attempt
hung inside the FIRST step — then the MoE compile — and recorded
nothing in 45 minutes).  Each step prints a STEP banner up front so the
log shows exactly where a wedge happened.

All timing uses the looped methodology (TPU_EVIDENCE.md): N iterations
inside one jitted fori_loop, one scalar readback.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

assert jax.devices()[0].platform == "tpu", jax.devices()
print("device:", jax.devices()[0], flush=True)


def step(name):
    print(f"STEP {name} @ {time.strftime('%H:%M:%S')}", flush=True)


def onchip_time(fn, args, est_ms, budget_ms=1500):
    iters = max(4, int(budget_ms / max(est_ms, 0.01)))

    @jax.jit
    def looped(*a):
        def body(i, acc):
            o = fn(*a)
            if isinstance(o, tuple):
                o = o[0]
            return acc + jnp.sum(o.reshape(-1)[:1].astype(jnp.float32))
        return lax.fori_loop(0, iters, body, 0.0)

    float(looped(*args))
    t0 = time.perf_counter()
    float(looped(*args))
    return (time.perf_counter() - t0) / iters


# -- 0. dispatch probe: a tiny matmul, so the log distinguishes "tunnel
# dead on arrival" from "hung inside a heavy compile" ------------------
step("probe")
rng = np.random.default_rng(0)
t0 = time.perf_counter()
_p = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
float(jnp.sum(jax.jit(lambda a: a @ a)(_p)))
print(f"probe matmul ok in {time.perf_counter()-t0:.2f}s", flush=True)

# -- 1. causal flash timing (fills the causal table) -----------------------
from learningorchestra_tpu.ops.attention import (  # noqa: E402
    flash_attention,
    mha_reference,
)

step("causal flash bwd timing")
for (b, h, t, d, est_ms) in [(1, 8, 4096, 64, 0.4), (1, 2, 32768, 64, 3)]:
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True, interpret=False
                        ).astype(jnp.float32)), argnums=(0, 1, 2))
    tb = onchip_time(lambda q, k, v: g(q, k, v)[0], (q, k, v), est_ms * 3)
    fl = 4 * b * h * t * t * d
    print(f"causal bwd B{b} H{h} T{t} D{d}: {tb*1e3:.2f} ms "
          f"({2.5*fl/2/tb/1e12:.0f} TF/s causal-effective)", flush=True)

# -- 2. sliding-window flash on chip: correctness + the band
# narrowing's O(T*W) scaling (time should track W, not T) -------------
step("window flash")
for (t, w, est_ms) in [(32768, 1024, 1), (32768, 4096, 2)]:
    q = jnp.asarray(rng.standard_normal((1, 2, t, 64)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, 2, t, 64)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 2, t, 64)), jnp.bfloat16)
    tw = onchip_time(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=w, interpret=False
        ), (q, k, v), est_ms,
    )
    band_fl = 4 * 2 * t * w * 64  # ~2*T*W keys per query pair of matmuls
    print(f"window flash T={t} W={w}: {tw*1e3:.2f} ms "
          f"(~{band_fl/tw/1e12:.0f} TF/s on the band)", flush=True)
# correctness at a padded/odd config
q = jnp.asarray(rng.standard_normal((2, 2, 1000, 64)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((2, 2, 1000, 64)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((2, 2, 1000, 64)), jnp.bfloat16)
ow = flash_attention(q, k, v, causal=True, window=100, interpret=False)
rw = mha_reference(
    q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
    causal=True, window=100,
)
werr = float(jnp.max(jnp.abs(ow.astype(jnp.float32) - rw)))
print(f"window flash correctness (T=1000, W=100): max err {werr:.4f}",
      flush=True)
assert werr < 0.05, werr

# -- 3. ring-flash on the chip (sp=1 degenerate ring: proves the
# shard_map + Pallas composition compiles and matches on real hardware;
# the multi-chip ring itself is validated on the virtual mesh) ---------
from learningorchestra_tpu.parallel.mesh import MeshSpec, build_mesh  # noqa: E402
from learningorchestra_tpu.parallel.ring_attention import (  # noqa: E402
    reference_attention,
    ring_flash_attention,
)

step("ring-flash sp=1")
mesh1 = build_mesh(MeshSpec(dp=1, sp=1))
b, t, h, d = 2, 2048, 4, 64
q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.bfloat16)
km = jnp.asarray(rng.random((b, t)) > 0.1)
o = ring_flash_attention(q, k, v, mesh=mesh1, kmask=km, causal=True)
ref = reference_attention(
    q.astype(jnp.float32), k.astype(jnp.float32),
    v.astype(jnp.float32), kmask=km, causal=True,
)
err = float(jnp.max(jnp.abs(o.astype(jnp.float32) - ref)))
print(f"ring-flash (sp=1) on chip: max err {err:.4f}", flush=True)
assert err < 0.05, err

# -- 4. KV-cache generate on chip ------------------------------------------
from learningorchestra_tpu.models.text import DecoderLM  # noqa: E402

step("KV-cache generate")
lm = DecoderLM(vocab_size=1000, hidden_dim=256, num_layers=4,
               num_heads=8, max_len=256)
xs = rng.integers(1, 1000, (16, 64), dtype=np.int32)
tg = np.concatenate([xs[:, 1:], np.zeros((16, 1), np.int32)], 1)
lm.fit(xs, tg, epochs=1, batch_size=16, verbose=0)
t0 = time.perf_counter()
out = lm.generate(xs[:4, :32], max_new_tokens=96)  # compile + run
t1 = time.perf_counter()
out = lm.generate(xs[:4, :32], max_new_tokens=96)  # cached fn
t2 = time.perf_counter()
assert out.shape == (4, 128)
print(f"KV-cache generate 96 tok ok: first {t1-t0:.1f}s (compile), "
      f"second {t2-t1:.2f}s -> {(t2-t1)/96*1e3:.1f} ms/token incl tunnel",
      flush=True)

# -- 5. RoPE + GQA + window decoder generates on chip ----------------
step("RoPE+GQA+window decoder")
rope_lm = DecoderLM(
    vocab_size=1000, hidden_dim=256, num_layers=2, num_heads=8,
    max_len=256, positional="rope", num_kv_heads=2,
    attention_window=64,
)
rope_lm.fit(xs, tg, epochs=1, batch_size=16, verbose=0)
out = rope_lm.generate(xs[:2, :16], max_new_tokens=32)
assert out.shape == (2, 48) and (out[:, 16:] != 0).any()
print("RoPE+GQA+window decoder generate ok on chip", flush=True)

# -- 6. MoE transformer train step on chip (the heaviest compile of the
# set — last, after everything else is on the record) ------------------
from learningorchestra_tpu.models.moe import MoETransformerClassifier  # noqa: E402

step("MoE train")
x = rng.integers(1, 1000, (64, 128), dtype=np.int32)
y = rng.integers(0, 2, (64,), dtype=np.int32)
est = MoETransformerClassifier(
    vocab_size=1000, hidden_dim=256, num_layers=4, num_heads=8,
    max_len=128, num_experts=8, mlp_dim=1024,
)
t0 = time.perf_counter()
est.fit(x, y, epochs=3, batch_size=32, verbose=0)
print(f"MoE train 3 epochs ok, loss={est.history['loss'][-1]:.4f} "
      f"({time.perf_counter()-t0:.1f}s incl compile)", flush=True)

# -- 7. fused-epoch bench runner -------------------------------------------
import subprocess, sys, os  # noqa: E402

step("bench.py")
r = subprocess.run([sys.executable, os.path.join(
    os.path.dirname(__file__), "..", "bench.py")],
    capture_output=True, text=True, timeout=1500)
print("bench.py:", r.stdout.strip().splitlines()[-1] if r.stdout else r.stderr[-500:],
      flush=True)
print("ALL ON-CHIP CHECKS DONE", flush=True)
