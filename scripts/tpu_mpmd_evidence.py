"""On-box MPMD pipeline evidence: run bench._mpmd_probe and print its
JSON — per-stage programs host-dispatched under 1F1B vs the same math
as one monolithic jitted program.  Short stage (~2-5 min): banks the
re-fit cold-compile advantage (per-stage compile-cache entries hit
with zero misses while a fresh monolithic wrapper re-pays its
whole-pipeline compile) and the steady-state host-dispatch overhead
bound the README section quotes.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bench import _mpmd_probe  # noqa: E402


def main() -> None:
    result = {"mpmd": _mpmd_probe()}
    probe = result["mpmd"]
    ratio = probe["steady_overhead_ratio"]
    misses = probe["refit_misses"]
    # Loud verdict line for the watch log; the JSON is the record.
    # Acceptance: a re-fit hits every per-stage cache entry (zero
    # misses) and the host 1F1B loop stays within 10% of the
    # monolithic step at steady state.
    ok = misses == 0 and ratio is not None and ratio <= 1.10
    print(
        f"mpmd refit misses {misses}, steady overhead {ratio}x "
        f"({'OK' if ok else 'REGRESSION: misses > 0 or > 1.10x'})",
        file=sys.stderr, flush=True,
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
