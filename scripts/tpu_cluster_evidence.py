"""Control-plane claim-path evidence: one JSON row for the writeup.

Runs bench.py's `_claim_probe` — store-backed claim CAS, heartbeat
renewal, full claim+release cycle, and a minimal no-op dispatch on the
same box — and prints the shares the acceptance bar is stated in
(claim-path overhead <= 5% of a minimal dispatch).  The probe is
platform-independent (no device work), but runs in the watch chain so
the number is banked on the SAME host and load profile as the rest of
the round's evidence.
"""

import json
import os
import sys
from pathlib import Path

# The dispatch floor runs ~1k no-op jobs; per-job INFO lines would
# swamp the banked log without adding evidence.
os.environ.setdefault("LO_TPU_LOG_LEVEL", "WARNING")
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def main() -> None:
    out = bench._claim_probe()
    print(json.dumps({"metric": "cluster_claim_probe", **out}),
          flush=True)


if __name__ == "__main__":
    main()
