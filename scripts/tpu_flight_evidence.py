"""On-box flight-recorder overhead evidence: run bench._flight_probe
and print its JSON — the hot-path ring append cost (enabled vs
disabled) and the debounced bundle-trigger cost, expressed as a share
of a single-row serving dispatch.  Short stage (~1-2 min): the probe
is host-side, so it banks a number whether or not the TPU tunnel
stays up, but running it in the chain records the number for the SAME
box and build the other stages measure.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bench import _flight_probe  # noqa: E402


def main() -> None:
    result = {"flight": _flight_probe()}
    share = result["flight"]["per_dispatch_share_pct"]
    # Loud verdict line for the watch log; the JSON is the record.
    print(
        f"flight append share {share}% of one dispatch "
        f"({'OK' if share <= 1.0 else 'REGRESSION: > 1%'})",
        file=sys.stderr, flush=True,
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
