"""On-chip validation of round-3 additions — run when the TPU tunnel is up.

    PYTHONPATH=/root/.axon_site:/root/repo python scripts/tpu_validate_r3.py

Covers (beyond scripts/tpu_validate_r2.py, which should also run):
1. streaming (sharded) fit on chip — shard prefetch + device_put overlap,
   throughput vs the device-resident path on the same data (target:
   within ~10% — VERDICT r3 item 3's done-bar);
2. int8 quantize/dequantize with the REAL Mosaic kernels
   (interpret=False) at embedding-table scale;
3. quantized-artifact save/load + predict parity on chip;
4. 1F1B single-stage degenerate step on the chip (pp=1 — multi-chip
   schedules are virtual-mesh-tested; this proves the manual-VJP step
   compiles and trains on real silicon).

Timing uses the looped/fused methodology (TPU_EVIDENCE.md) so tunnel
round-trips cancel: both sides here time MULTI-epoch fits (epochs>=3)
whose per-epoch dispatch count is identical, so the constant per-call
tunnel cost washes out of the ratio.
"""

import json
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

assert jax.devices()[0].platform == "tpu", jax.devices()
print("device:", jax.devices()[0], flush=True)

rng = np.random.default_rng(0)


def step(name):
    import time as _t
    print(f"STEP {name} @ {_t.strftime('%H:%M:%S')}", flush=True)

step("probe")
_p = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
assert float(jnp.sum(jax.jit(lambda a: a @ a)(_p)) ** 0) == 1.0
print("probe matmul ok", flush=True)

step("streaming vs resident")
# -- 1. streaming fit vs device-resident, same data ------------------------
# Wide-MLP on flat features: the tabular surface sharded ingest feeds.
from learningorchestra_tpu.models.mlp import MLPClassifier  # noqa: E402
from learningorchestra_tpu.store.sharded import (  # noqa: E402
    ShardedDataset,
    ShardedDatasetWriter,
)

n, d, shard_rows, bs, epochs = 65536, 256, 16384, 1024, 3
x = rng.standard_normal((n, d)).astype(np.float32)
w_true = rng.standard_normal((d, 10))
y = np.argmax(x @ w_true, axis=1).astype(np.int32)

tmp = tempfile.mkdtemp()
writer = ShardedDatasetWriter(
    tmp + "/tab", [f"f{i}" for i in range(d)] + ["label"],
    rows_per_shard=shard_rows,
)
for i in range(n):
    writer.append(list(x[i]) + [int(y[i])])
writer.close()
ds = ShardedDataset(tmp + "/tab")

def _fit_sps(est, fit_x, fit_y):
    est.fit(fit_x, fit_y, epochs=1, batch_size=bs)  # compile epoch fns
    t0 = time.perf_counter()
    est.fit(fit_x, fit_y, epochs=epochs, batch_size=bs)
    return epochs * n / (time.perf_counter() - t0)

resident_sps = _fit_sps(
    MLPClassifier(hidden_layer_sizes=[1024, 1024], num_classes=10), x, y
)
streaming_sps = _fit_sps(
    MLPClassifier(hidden_layer_sizes=[1024, 1024], num_classes=10),
    ds.feature_view("label"), ds["label"],
)
print(json.dumps({
    "check": "streaming_vs_resident",
    "resident_samples_per_sec": round(resident_sps, 1),
    "streaming_samples_per_sec": round(streaming_sps, 1),
    "ratio": round(streaming_sps / resident_sps, 3),
    "ok": streaming_sps >= 0.9 * resident_sps,
}), flush=True)

step("int8 kernels")
# -- 2. int8 kernels for real (interpret=False) ----------------------------
from learningorchestra_tpu.ops.quant import (  # noqa: E402
    dequantize_rowwise,
    quantize_rowwise,
)

mat = jnp.asarray(rng.standard_normal((30522, 768)), jnp.float32)
v, s = quantize_rowwise(mat, stochastic=False, interpret=False)
back = dequantize_rowwise(v, s, interpret=False)
err = float(jnp.max(jnp.abs(back - mat)))
bound = float(jnp.max(jnp.abs(mat), axis=1).max()) / 127.0
print(json.dumps({
    "check": "quant_kernels_hw",
    "max_err": round(err, 6),
    "bound": round(bound, 6),
    "ok": err <= bound + 1e-6,
}), flush=True)

step("quant artifact")
# -- 3. quantized artifact round trip on chip ------------------------------
import dill  # noqa: E402

xa = rng.standard_normal((512, 64)).astype(np.float32)
wa = rng.standard_normal((64, 3))
ya = np.argmax(xa @ wa, axis=1).astype(np.int32)
mlp = MLPClassifier(hidden_layer_sizes=[256, 256], num_classes=3)
mlp.fit(xa, ya, epochs=10, batch_size=128, quantize_checkpoint=True)
blob = dill.dumps(mlp)
loaded = dill.loads(blob)
agree = float(
    (mlp.predict_classes(xa) == loaded.predict_classes(xa)).mean()
)
print(json.dumps({
    "check": "quant_artifact_hw",
    "blob_kb": len(blob) // 1024,
    "pred_agreement": round(agree, 4),
    "ok": agree > 0.97,
}), flush=True)

step("1f1b pp=1")
# -- 4. 1F1B degenerate (pp=1) train step on chip --------------------------
from learningorchestra_tpu.parallel.pipeline import (  # noqa: E402
    PipelinedTransformer,
)

xt = rng.integers(1, 1000, (64, 128), dtype=np.int32)
yt = rng.integers(0, 2, (64,), dtype=np.int32)
pt = PipelinedTransformer(
    vocab_size=1000, hidden_dim=256, num_layers=2, num_heads=8,
    max_len=128, pp=1, schedule="1f1b",
)
pt.fit(xt, yt, epochs=2, batch_size=64)
print(json.dumps({
    "check": "1f1b_hw",
    "loss": [round(v, 4) for v in pt.history["loss"]],
    "ok": bool(np.isfinite(pt.history["loss"][-1])),
}), flush=True)

step("kv decode")
# -- 5. KV-cache decode throughput (tokens/sec) ----------------------------
from learningorchestra_tpu.models.text import DecoderLM  # noqa: E402

lm = DecoderLM(
    vocab_size=32000, hidden_dim=512, num_layers=8, num_heads=8,
    max_len=1024,
)
prompts = rng.integers(1, 32000, (8, 64)).astype(np.int32)
lm._init_params(jnp.asarray(prompts[:1]))
new_tokens = 256
out = lm.generate(prompts, max_new_tokens=new_tokens)  # compile
t0 = time.perf_counter()
out = lm.generate(prompts, max_new_tokens=new_tokens)
dt = time.perf_counter() - t0
tps = prompts.shape[0] * new_tokens / dt
print(json.dumps({
    "check": "kv_decode_hw",
    "batch": 8, "prompt": 64, "new_tokens": new_tokens,
    "tokens_per_sec": round(tps, 1),
    "note": "one jitted scan; single dispatch — tunnel RT amortized",
}), flush=True)

print("R3 VALIDATION DONE", flush=True)
