"""On-box durable-warm-start evidence: run bench._warmboot_probe and
print its JSON — first-dispatch latency into a fresh compile cache,
cold (trace + XLA compile) vs pre-warmed from an AOT-serialized
executable (train/aot_store.py).  Short stage (~1-3 min): on TPU the
cold side pays the real seconds-per-program trace+compile bill a
restart would, so the banked speedup is the restart-recovery number
the README section quotes.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bench import _warmboot_probe  # noqa: E402


def main() -> None:
    result = {"warmboot": _warmboot_probe()}
    speedup = result["warmboot"]["speedup"]
    # Loud verdict line for the watch log; the JSON is the record.
    # >= 3x is the acceptance bar: below it the durable store isn't
    # paying for its deserialize on this backend.
    print(
        f"warmboot first-dispatch speedup {speedup}x "
        f"({'OK' if speedup is not None and speedup >= 3.0 else 'REGRESSION: < 3x'})",
        file=sys.stderr, flush=True,
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
